"""Mixed-mode continuous batching: `PagedDecodeServer(prefill_budget=N)`
fuses admission prefill into the decode dispatch (runtime/schedule.py
plans it, runtime/paged.py::_tick_mixed runs it), and nothing the user
can observe moves — outputs are token-identical to the stall path
(prefill_budget=None) across attention modes, prefix caching, fused
windows, tensor parallelism, sampling, eos and stop sequences.

The perf claim in miniature, pinned by counters because a parity test
alone can't see it: while a prompt prefills, every live decode slot
still advances exactly one token per tick, and the stall counter
(`defer_prefill_stall_ticks_total` — admission-prefill dispatches
issued with decode slots live) stays at zero in mixed mode.

Also here: the admission-queue deque pin (pop-from-head must be O(1),
not a list pop(0) that scans the tail of a deep backlog) and the
strict `_submit_t` ledger contract on both servers — a rid without a
submit timestamp is a loud KeyError, never a silently-zero queue
wait, and the ledger drains empty when serving completes (ttft pops
at the drain point, so ttft spans queue + prefill).
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.runtime.decode_server import DecodeServer
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged
from defer_tpu.runtime.schedule import (
    PrefillSeat,
    plan_mixed_tick,
    pow2_bucket,
)


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


def _requests(vocab):
    """Shared prefix on the first two (radix hits under prefix_cache),
    one prompt long enough to span several budgeted chunks."""
    rng = np.random.default_rng(3)
    base = jnp.asarray(rng.integers(1, vocab, size=(1, 6)), jnp.int32)
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 4)), jnp.int32)
    return [
        (base, 7),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 11)), jnp.int32), 6),
    ]


def _assert_identical(a, b):
    for i, (x, y) in enumerate(zip(a, b)):
        assert x.shape == y.shape and bool(jnp.all(x == y)), (
            i,
            np.asarray(x),
            np.asarray(y),
        )


# -- host-side planner -------------------------------------------------


def test_seat_chunk_progress():
    seat = PrefillSeat(rid=1, tokens=np.arange(5), base=8, keep_from=0)
    assert (seat.remaining, seat.pos, seat.finished) == (5, 8, False)
    assert list(seat.take(3)) == [0, 1, 2]
    assert (seat.remaining, seat.pos) == (2, 11)
    assert list(seat.take(2)) == [3, 4]
    assert seat.finished
    with pytest.raises(ValueError):
        seat.take(1)


def test_seat_rejects_empty_suffix():
    with pytest.raises(ValueError, match="at least one token"):
        PrefillSeat(rid=1, tokens=np.zeros((0,)), base=0, keep_from=0)


def test_plan_respects_budget_chunk_cap_and_t_limit():
    # Budget rations across seats in admission order.
    t, ns = plan_mixed_tick([10, 10], budget=6, chunk_cap=8, t_limit=8)
    assert ns == [6, 0] and t == 8  # pow2 bucket of 6
    # chunk_cap bounds any single seat's slice.
    t, ns = plan_mixed_tick([10, 10], budget=8, chunk_cap=3, t_limit=8)
    assert ns == [3, 3] and t == 4
    # t_limit clamps the bucketed T (lane-clamp invariant).
    t, ns = plan_mixed_tick([10], budget=8, chunk_cap=8, t_limit=5)
    assert ns == [5] and t == 5
    # No seats: decode rows still ride at T=1.
    t, ns = plan_mixed_tick([], budget=4, chunk_cap=4, t_limit=4)
    assert ns == [] and t == 1


def test_pow2_bucket():
    assert [pow2_bucket(n, 16) for n in (1, 2, 3, 5, 9, 17)] == [
        1, 2, 4, 8, 16, 16,
    ]


# -- token identity vs the stall path ----------------------------------

# attention x prefix_cache x decode_window; every mixed tick body
# composition appears at least once without the full product.
MATRIX = [
    ("gathered", False, 1),
    ("gathered", True, 1),
    ("gathered", False, 8),
    ("gathered", True, 8),
    ("blockwise", False, 1),
    ("blockwise", True, 8),
]


@pytest.mark.parametrize("attention,prefix_cache,window", MATRIX)
def test_mixed_token_identity(model, attention, prefix_cache, window):
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    kw = dict(
        num_blocks=32,
        block_size=4,
        max_batch=2,
        attention=attention,
        prefix_cache=prefix_cache,
        decode_window=window,
    )
    base, _ = serve_paged(dec, params, reqs, **kw)
    mixed, stats = serve_paged(
        dec, params, reqs, prefill_budget=4, **kw
    )
    _assert_identical(base, mixed)
    assert stats["mixed_ticks"] > 0
    assert stats["prefill_stall_ticks"] == 0
    assert stats["decode_stall_fraction"] == 0.0


def test_mixed_token_identity_tp2(model):
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    base, _ = serve_paged(
        dec, params, reqs, num_blocks=32, block_size=4, max_batch=2
    )
    mesh = make_mesh({"model": 2}, jax.devices()[:2])
    mixed, _ = serve_paged(
        dec,
        params,
        reqs,
        num_blocks=32,
        block_size=4,
        max_batch=2,
        prefill_budget=4,
        mesh=mesh,
    )
    _assert_identical(base, mixed)


def test_mixed_sampled_eos_stop_parity(model):
    """Seeded sampling, eos, and stop sequences on decode rows all
    fire identically while other seats are mid-prefill: the sampler's
    key stream is decode-row-driven, so budgeted prefill chunks must
    not consume draws."""
    dec, params = model
    rng = np.random.default_rng(7)
    prompts = [
        jnp.asarray(rng.integers(1, 64, size=(1, n)), jnp.int32)
        for n in (5, 9, 13, 4)
    ]
    steps = [12, 10, 8, 6]
    samp = [
        SamplingParams(temperature=0.8, top_k=8, seed=11),
        None,
        SamplingParams(temperature=1.0, top_p=0.9, seed=3),
        None,
    ]
    stops = [None, [[2, 5]], None, [[1]]]

    def run(budget):
        srv = PagedDecodeServer(
            dec,
            params,
            num_blocks=48,
            block_size=4,
            max_batch=2,
            eos_id=9,
            prefill_budget=budget,
        )
        rids = [
            srv.submit(p, s, sampling=sp, stop=st)
            for p, s, sp, st in zip(prompts, steps, samp, stops)
        ]
        while srv.pending or any(s is not None for s in srv.slots):
            srv._admit()
            if any(s is not None for s in srv.slots):
                srv._tick()
        return [srv.done[r] for r in rids]

    _assert_identical(run(None), run(3))


# -- the stall-free claim, pinned by counters --------------------------


def test_decode_never_skips_a_tick_while_prompt_prefills(model):
    """The tentpole claim: with a budget set, a decoding slot emits
    exactly one token on EVERY tick a prompt spends prefilling — no
    tick is surrendered to admission — and the stall counter stays 0."""
    dec, params = model
    rng = np.random.default_rng(5)
    srv = PagedDecodeServer(
        dec,
        params,
        num_blocks=48,
        block_size=4,
        max_batch=2,
        prefill_budget=2,
    )
    short = jnp.asarray(rng.integers(1, 64, size=(1, 4)), jnp.int32)
    long = jnp.asarray(rng.integers(1, 64, size=(1, 17)), jnp.int32)
    srv.submit(short, 32)
    srv._admit()
    # Run the first request's own admission prefill out (no decode
    # slot is live yet, so these ticks cannot stall anyone).
    while any(s is not None and "prefill" in s for s in srv.slots):
        srv._tick()
    (i0,) = [
        i for i, s in enumerate(srv.slots) if s is not None
    ]
    # A long prompt arrives mid-decode: with budget=2 its 17-token
    # suffix spans many ticks, every one of which must also advance
    # the decoding slot.
    srv.submit(long, 4)
    srv._admit()
    assert any(
        s is not None and "prefill" in s for s in srv.slots
    ), "long prompt should be seated mid-prefill"
    prefill_ticks = 0
    while any(s is not None and "prefill" in s for s in srv.slots):
        before = len(srv.slots[i0]["toks"])
        srv._tick()
        assert len(srv.slots[i0]["toks"]) == before + 1, (
            "decode slot skipped a tick while the prompt prefilled"
        )
        prefill_ticks += 1
    assert prefill_ticks >= 3  # the claim exercised, not vacuous
    assert srv.prefill_stall_ticks_n == 0
    assert srv.decode_stall_fraction_last == 0.0
    assert srv.mixed_prefill_tokens_n >= long.shape[1]
    # Drain; the ledger empties (strict-ttft drain contract below).
    while srv.pending or any(s is not None for s in srv.slots):
        srv._admit()
        if any(s is not None for s in srv.slots):
            srv._tick()
    assert srv._submit_t == {}


def test_stall_path_counts_stalls(model):
    """The baseline the budget removes: stall-mode admission of a
    prompt while a slot decodes increments the stall counters."""
    dec, params = model
    rng = np.random.default_rng(5)
    srv = PagedDecodeServer(
        dec, params, num_blocks=48, block_size=4, max_batch=2
    )
    srv.submit(
        jnp.asarray(rng.integers(1, 64, size=(1, 4)), jnp.int32), 16
    )
    srv._admit()
    srv._tick()
    srv.submit(
        jnp.asarray(rng.integers(1, 64, size=(1, 12)), jnp.int32), 4
    )
    srv._admit()  # stall-path prefill with a live decode slot
    assert srv.prefill_stall_ticks_n >= 1
    assert srv.decode_stall_fraction_last > 0.0


# -- construction contract ---------------------------------------------


def test_budget_rejects_speculation(model):
    dec, params = model
    with pytest.raises(ValueError, match="prefill_budget=None server"):
        PagedDecodeServer(
            dec,
            params,
            num_blocks=16,
            block_size=4,
            prefill_budget=8,
            spec_k=2,
            spec_draft=dec,
            spec_params=params,
        )


def test_budget_rejects_pipeline_stages(model):
    dec, params = model
    with pytest.raises(ValueError, match="pp_stages=1"):
        PagedDecodeServer(
            dec,
            params,
            num_blocks=16,
            block_size=4,
            prefill_budget=8,
            pp_stages=2,
        )


def test_budget_validation(model):
    dec, params = model
    with pytest.raises(ValueError, match="prefill_budget"):
        PagedDecodeServer(
            dec, params, num_blocks=16, block_size=4, prefill_budget=0
        )
    with pytest.raises(ValueError, match="prefill_lookahead"):
        PagedDecodeServer(
            dec,
            params,
            num_blocks=16,
            block_size=4,
            prefill_budget=4,
            prefill_lookahead=0,
        )


# -- admission queue + strict _submit_t ledger -------------------------


def test_pending_queues_are_deques(model):
    """Depth-scaling pin: admission pops the head once per freed seat,
    so the queue must be a deque (O(1) popleft) on BOTH servers — a
    list's pop(0) scans the whole tail of a deep backlog on every
    admission."""
    dec, params = model
    paged = PagedDecodeServer(dec, params, num_blocks=16, block_size=4)
    flat = DecodeServer(dec, params, max_batch=2)
    assert isinstance(paged.pending, collections.deque)
    assert isinstance(flat.pending, collections.deque)
    # Deep backlog drains head-first in submission order.
    rng = np.random.default_rng(0)
    prompts = [
        jnp.asarray(rng.integers(1, 64, size=(1, 3)), jnp.int32)
        for _ in range(64)
    ]
    rids = [paged.submit(p, 1) for p in prompts]
    seen = [paged.pending.popleft()[0] for _ in range(64)]
    assert seen == rids


@pytest.mark.parametrize("server", ["paged", "flat"])
def test_unknown_rid_is_loud(model, server):
    """A pending entry without a submit timestamp must raise at
    admission, not observe a silently-zero queue wait."""
    dec, params = model
    prompt = jnp.asarray([[3, 9, 27]], jnp.int32)
    if server == "paged":
        srv = PagedDecodeServer(
            dec, params, num_blocks=16, block_size=4, max_batch=1
        )
        srv.pending.append((999, prompt, 2, 0, None, None, 0))
        with pytest.raises(KeyError):
            srv._admit()
    else:
        srv = DecodeServer(dec, params, max_batch=1)
        srv.pending.append((999, prompt, 2, 0, None, None, 0))
        with pytest.raises(KeyError):
            srv._admit()


@pytest.mark.parametrize("budget", [None, 4])
def test_submit_ledger_drains_and_ttft_spans_queue(model, budget):
    """On both admit paths (stall and mixed): the ledger is empty once
    serving completes (every rid's timestamp popped exactly once, at
    first token), and each request's ttft >= its queue wait because
    ttft additionally spans the prefill."""
    from defer_tpu.obs import reset as obs_reset

    obs_reset()  # global registry: drop other tests' observations
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    srv = PagedDecodeServer(
        dec,
        params,
        num_blocks=32,
        block_size=4,
        max_batch=2,
        prefill_budget=budget,
    )
    rids = [srv.submit(p, s) for p, s in reqs]
    while srv.pending or any(s is not None for s in srv.slots):
        srv._admit()
        if any(s is not None for s in srv.slots):
            srv._tick()
    assert sorted(srv.done) == sorted(rids)
    assert srv._submit_t == {}
    reg = srv.obs.registry
    lab = {"server": "paged"}
    ttft = reg.value("defer_ttft_seconds", **lab)
    qw = reg.value("defer_queue_wait_seconds", **lab)
    assert ttft["count"] == len(reqs) == qw["count"]
    assert ttft["sum"] >= qw["sum"]


def test_submit_prefilled_bypasses_budget(model):
    """submit_prefilled ships landed KV — there is no prefill to
    budget, so prefilled admissions take a slot immediately even on a
    budgeted server and never touch the stall counters."""
    dec, params = model
    prompt = jnp.asarray([[3, 9, 27, 4]], jnp.int32)
    mono = PagedDecodeServer(
        dec, params, num_blocks=32, block_size=4, max_batch=1
    )
    r0 = mono.submit(prompt, 5)
    mono._admit()
    while any(s is not None for s in mono.slots):
        mono._tick()
    expect = mono.done[r0]

    from defer_tpu.disagg.prefill_worker import run_prefill

    srv = PagedDecodeServer(
        dec,
        params,
        num_blocks=32,
        block_size=4,
        max_batch=1,
        prefill_budget=2,
    )
    rid = srv.submit_prefilled(prompt, 5)
    k_blocks, v_blocks, logits_row = run_prefill(
        dec, params, np.asarray(prompt), block_size=4
    )
    srv.deliver_kv(rid, k_blocks, v_blocks, logits_row)
    srv._admit()
    assert any(
        s is not None and "prefill" not in s for s in srv.slots
    ), "prefilled admission must seat as a decoding slot immediately"
    while any(s is not None for s in srv.slots):
        srv._tick()
    _assert_identical([expect], [srv.done[rid]])
    assert srv.mixed_prefill_tokens_n == 0
