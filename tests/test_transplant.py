"""Weight transplant: layout conversion round trips and a real
torch -> JAX numerical equivalence check (SURVEY.md §7 hard part #2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import get_model
from defer_tpu.models.transplant import (
    KerasWeights,
    TorchStateDict,
    TransplantError,
    export_keras_weights,
    load_keras_h5,
    transplant,
)


def test_keras_round_trip_mobilenetv2():
    """export -> import reproduces every array bit-exactly, including
    the depthwise kernel reshape."""
    model = get_model("mobilenetv2")
    params = model.graph.init(jax.random.key(0), (1, 96, 96, 3))
    kw = export_keras_weights(model.graph, params)
    back = transplant(model.graph, params, KerasWeights(kw))
    for name, node_params in params.items():
        for p, v in node_params.items():
            np.testing.assert_array_equal(np.asarray(v), np.asarray(back[name][p]))


def test_keras_h5_round_trip(tmp_path):
    """Write a Keras-layout h5 and read it back via load_keras_h5."""
    from conftest import write_keras_h5

    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(1), (1, 224, 224, 3))
    kw = export_keras_weights(model.graph, params)
    path = str(tmp_path / "w.h5")
    write_keras_h5(path, kw)
    loaded = load_keras_h5(path)
    back = transplant(model.graph, params, KerasWeights(loaded))
    np.testing.assert_array_equal(
        np.asarray(params["block3_conv2"]["kernel"]),
        np.asarray(back["block3_conv2"]["kernel"]),
    )


def test_torch_transplant_matches_torch_forward():
    """Build the same small CNN in torch and in the IR, transplant the
    torch state_dict, and require matching outputs — covers OIHW->HWIO,
    depthwise grouping order, linear transpose, and BN statistics."""
    torch = pytest.importorskip("torch")
    torch.manual_seed(0)

    class Net(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1)
            self.bn1 = torch.nn.BatchNorm2d(8)
            self.dw = torch.nn.Conv2d(8, 16, 3, padding=1, groups=8)
            self.bn2 = torch.nn.BatchNorm2d(16)
            self.fc = torch.nn.Linear(16, 10)

        def forward(self, x):
            x = torch.relu(self.bn1(self.conv1(x)))
            x = torch.relu(self.bn2(self.dw(x)))
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    # Prime in train mode so running_mean/var move off their 0/1
    # defaults (which would mask a failure to transplant them), then
    # freeze for the comparison.
    net = Net().train()
    with torch.no_grad():
        net(torch.randn(16, 3, 16, 16))
    net.eval()
    assert float(net.bn1.running_mean.abs().sum()) > 0

    b = GraphBuilder("tiny")
    x = b.input("input")
    x = b.add("conv", x, name="conv1", features=8, kernel_size=3, strides=2,
              padding=((1, 1), (1, 1)), use_bias=True)
    x = b.add("batch_norm", x, name="bn1", eps=1e-5)
    x = b.add("relu", x, name="relu1")
    x = b.add("depthwise_conv", x, name="dw", kernel_size=3,
              padding=((1, 1), (1, 1)), depth_multiplier=2, use_bias=True)
    x = b.add("batch_norm", x, name="bn2", eps=1e-5)
    x = b.add("relu", x, name="relu2")
    x = b.add("global_avg_pool", x, name="gap")
    x = b.add("dense", x, name="fc", features=10)
    graph = b.build(x)

    params = graph.init(jax.random.key(0), (2, 16, 16, 3))
    loaded = transplant(graph, params, TorchStateDict(net.state_dict()))

    xin = np.random.default_rng(3).standard_normal((2, 16, 16, 3)).astype(
        np.float32
    )
    want = net(torch.from_numpy(np.transpose(xin, (0, 3, 1, 2)))).detach().numpy()
    got = np.asarray(graph.apply(loaded, jnp.asarray(xin)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_transplant_strict_raises_on_missing():
    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    with pytest.raises(TransplantError, match="no weights"):
        transplant(model.graph, params, KerasWeights({}))
    # Non-strict keeps initialized values.
    out = transplant(model.graph, params, KerasWeights({}), strict=False)
    np.testing.assert_array_equal(
        np.asarray(out["fc1"]["kernel"]), np.asarray(params["fc1"]["kernel"])
    )


def test_keras_bn_scale_false_front_omission():
    """Keras BatchNormalization(scale=False) omits gamma from the FRONT
    of get_weights(); the remaining three must land on bias/mean/var."""
    b = GraphBuilder("bn")
    x = b.input("input")
    x = b.add("conv", x, name="c", features=4, kernel_size=1, use_bias=False)
    x = b.add("batch_norm", x, name="bn", eps=1e-3)
    graph = b.build(x)
    params = graph.init(jax.random.key(0), (1, 4, 4, 3))
    beta = np.full(4, 2.0, np.float32)
    mean = np.full(4, 3.0, np.float32)
    var = np.full(4, 4.0, np.float32)
    kw = {"c": [np.zeros((1, 1, 3, 4), np.float32)], "bn": [beta, mean, var]}
    out = transplant(graph, params, KerasWeights(kw))
    np.testing.assert_array_equal(np.asarray(out["bn"]["bias"]), beta)
    np.testing.assert_array_equal(np.asarray(out["bn"]["mean"]), mean)
    np.testing.assert_array_equal(np.asarray(out["bn"]["var"]), var)
    # gamma keeps its initialized value (ones)
    np.testing.assert_array_equal(np.asarray(out["bn"]["scale"]), np.ones(4))
    # center=False flavor: the missing param is beta instead.
    out2 = transplant(
        graph, params, KerasWeights(kw, bn_missing="bias")
    )
    np.testing.assert_array_equal(np.asarray(out2["bn"]["scale"]), beta)
    np.testing.assert_array_equal(np.asarray(out2["bn"]["bias"]), np.zeros(4))


def test_torch_partial_transplant_skips_unknown_ops():
    """strict=False over a graph with ops the torch mapping doesn't
    cover must keep their initialized values, not crash."""
    b = GraphBuilder("mixed")
    x = b.input("input")
    x = b.add("embedding", x, name="emb", vocab_size=8, features=4)
    x = b.add("layer_norm", x, name="ln")
    graph = b.build(x)
    import jax.numpy as jnp_

    params = graph.init(
        jax.random.key(0), (1, 3), input_dtype=jnp_.int32
    )
    torch = pytest.importorskip("torch")

    sd = {"ln.weight": torch.ones(4) * 5, "ln.bias": torch.zeros(4)}
    out = transplant(graph, params, TorchStateDict(sd), strict=False)
    np.testing.assert_array_equal(np.asarray(out["ln"]["scale"]), np.full(4, 5.0))
    np.testing.assert_array_equal(
        np.asarray(out["emb"]["table"]), np.asarray(params["emb"]["table"])
    )


def test_unused_checkpoint_keys_warn(caplog):
    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    kw = export_keras_weights(model.graph, params)
    kw["tpyo_layer"] = [np.zeros(3, np.float32)]
    import logging

    # The package logger doesn't propagate to root, so attach caplog's
    # handler to it directly.
    lg = logging.getLogger("defer_tpu")
    lg.addHandler(caplog.handler)
    try:
        transplant(model.graph, params, KerasWeights(kw))
    finally:
        lg.removeHandler(caplog.handler)
    assert any("unused" in r.getMessage() for r in caplog.records)


def test_transplant_shape_mismatch_raises():
    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    kw = export_keras_weights(model.graph, params)
    kw["block1_conv1"] = [np.zeros((3, 3, 4, 64), np.float32)]
    with pytest.raises(TransplantError, match="shape mismatch"):
        transplant(model.graph, params, KerasWeights(kw), strict=False)


# -- pretrained checkpoint resolution (defer_tpu/models/pretrained.py) ----


def test_load_pretrained_missing_path_skips_cleanly():
    from defer_tpu.models.pretrained import (
        PretrainedUnavailable,
        load_pretrained,
    )

    with pytest.raises(PretrainedUnavailable, match="does not exist"):
        load_pretrained("resnet50", "/nonexistent/weights.h5")


def test_load_pretrained_unwired_model_skips_cleanly():
    from defer_tpu.models.pretrained import (
        PretrainedUnavailable,
        load_pretrained,
    )

    # inceptionv3 has no tf.keras builder wired in pretrained.py (and
    # some zoo models have no keras_name_map at all) — either way the
    # error must be the catchable skip signal, not a KeyError.
    with pytest.raises(PretrainedUnavailable):
        load_pretrained("inceptionv3", "imagenet")


def test_load_pretrained_local_h5_roundtrip(tmp_path):
    """Export a zoo model's weights as a Keras h5, reload through
    load_pretrained's local-path branch, and require the transplanted
    forward to match the original exactly."""
    from defer_tpu.models.pretrained import load_pretrained
    from defer_tpu.models.transplant import export_keras_weights

    from conftest import write_keras_h5

    model = get_model("vgg16")
    params = model.init(jax.random.key(1))
    kw = {
        model.keras_name_map(layer): arrays
        for layer, arrays in export_keras_weights(
            model.graph, params
        ).items()
    }
    path = str(tmp_path / "vgg16.h5")
    write_keras_h5(path, kw)

    model2, params2, tf_model = load_pretrained("vgg16", path)
    assert tf_model is None
    x = np.random.RandomState(0).rand(1, 224, 224, 3).astype("float32")
    np.testing.assert_allclose(
        np.asarray(model2.graph.apply(params2, x)),
        np.asarray(model.graph.apply(params, x)),
        rtol=1e-5,
        atol=1e-6,
    )
