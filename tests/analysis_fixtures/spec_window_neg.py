"""NEGATIVE: the fused spec x window shape that ships
(runtime/paged.py::_tick_spec_window) — ONE jitted scan program runs
all W draft+verify rounds on device, then a single batched drain of
the per-round outputs, each transfer justified in place. The scan
body itself (draft propose + verify forward + accept test + pend
recurrence) never appears here: it is traced once, passed to the scan
by value, and stays on device."""

import numpy as np


class Server:
    def _tick(self):
        toks, kept = self._window_program(self.params, self.state)
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # [B, W, k+1] token drain per fused window — W whole rounds
        # amortize it
        toks_host = np.asarray(toks)
        # analysis: ignore[host-sync-in-hot-loop] kept-lengths half of
        # the same batched window drain
        kept_host = np.asarray(kept)
        for r in range(toks_host.shape[1]):
            self._commit(r, toks_host[:, r], kept_host[:, r])
