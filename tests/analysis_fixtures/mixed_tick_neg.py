"""NEGATIVE: the fused mixed-mode tick — decode rows plus up to
`budget` prompt tokens ride ONE jitted dispatch, planned entirely from
host-side seat bookkeeping (numpy ints, no device values), so the tick
issues no prefill-side syncs at all and decode never skips a tick."""


class Server:
    def _tick(self):
        # Host-side plan over host-side seat state: which rows are
        # decode, which carry prompt chunks, and the fused width T.
        t, ns = self._plan(self.budget)
        ids, n_keep, keep_from = self._pack(t, ns)
        # One fused dispatch carries decode AND prefill rows; the
        # result stays on device (sampling feeds the next tick's
        # persistent feed buffer by device-side update).
        logits, self.cache = self.step(
            self.params, self.cache, ids, n_keep, keep_from
        )
        self._feed = self._advance(logits)

    def _plan(self, budget):
        ns = []
        left = budget
        for seat in self.seats:
            n = min(seat.remaining, left)
            ns.append(n)
            left -= n
        return max(ns, default=0), ns
