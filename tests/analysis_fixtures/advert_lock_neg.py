"""NEGATIVE: the advertisement discipline fleet/router.py requires —
snapshot the digest set UNDER the radix lock (cheap, bounded), publish
OUTSIDE it. The serving thread's register/evict never wait on the
fanout."""


class Replica:
    def publish_adverts(self):
        with self.radix._lock:
            digests = frozenset(self.radix.by_key)
        self._board_sock.sendall(encode(digests))

    def close(self):
        with self.radix._lock:
            self._closing = True
        self._advert_thread.join()
