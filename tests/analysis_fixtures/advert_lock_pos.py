"""POSITIVE: digest-advertisement publish path that does blocking work
while holding the radix lock — `register`/`evict` on the serving
thread take the SAME lock, so admission stalls behind the
advertisement fanout (the anti-pattern fleet/router.py documents)."""


class Replica:
    def publish_adverts(self):
        with self.radix._lock:
            digests = frozenset(self.radix.by_key)
            self._board_sock.sendall(encode(digests))  # fanout under the lock

    def close(self):
        with self.radix._lock:
            self._advert_thread.join()  # unbounded wait under the lock
