"""NEGATIVE: conforming names — defer_ prefix, counters end _total,
one instrument kind per name."""

from defer_tpu.obs.metrics import get_registry

reg = get_registry()
ticks = reg.counter("defer_serving_ticks_total", "Ticks run")
depth = reg.gauge("defer_queue_depth", "Pending requests")
lat = reg.histogram("defer_tick_seconds", "Tick latency")
