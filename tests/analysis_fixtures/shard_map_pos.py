"""POSITIVE: host syncs inside a shard_map-wrapped tick body. The
body is a nested def passed to `shard_map` BY NAME from a builder the
`_tick` root reaches — the wrapper edge must carry hotness through,
so both the sync inside the sharded body and the one in a helper it
calls must flag."""

import numpy as np
from defer_tpu.utils.compat import shard_map


class Server:
    def _tick(self):
        step = self._build_step()
        logits, self.pool = step(self.params, self.pool, self.feed)

    def _build_step(self):
        def body(params, pool, feed):
            x = self._embed(params, feed)
            depth = feed.item()  # per-tick sync INSIDE the sharded body
            return self._attend(params, pool, x, depth), pool

        return shard_map(
            body, self.mesh,
            in_specs=(None, None, None), out_specs=(None, None),
        )

    def _attend(self, params, pool, x, depth):
        rows = np.asarray(pool[:depth])  # reachable through the body
        return x @ rows
