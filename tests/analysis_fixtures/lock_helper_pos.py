"""POSITIVE fixture: lock-discipline through one callgraph level.

The blocking call is hidden one call deep: ``push`` holds the lock
while calling ``_send_frame``, whose own body does the ``sendall``.
The pre-PR rule only saw lexically-direct blocking calls and missed
this; the helper's body is clean on its own (no lock held there), so
the single finding must land on the call site under the lock.

Expected: 1 finding.
"""

import threading


class Framer:
    def __init__(self, sock):
        self.sock = sock
        self._lock = threading.Lock()

    def _send_frame(self, payload):
        header = len(payload).to_bytes(4, "big")
        self.sock.sendall(header + payload)

    def push(self, payload):
        with self._lock:
            self._send_frame(payload)  # blocks inside, lock held
