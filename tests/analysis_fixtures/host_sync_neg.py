"""NEGATIVE: the same transfers in cold (non-serving) code, and a
clean hot loop that stays on-device."""

import numpy as np


def export_summary(results):
    # Cold path: export runs once after serving, syncs are fine here.
    return [np.asarray(r) for r in results]


class Server:
    def _tick(self):
        self.state = self._advance(self.state)

    def _advance(self, state):
        return state
