"""POSITIVE fixture: shard-spec psum-mirror drift.

The host-side ``_psums_per_fwd`` mirror claims 3 collectives per layer
but the per-layer trio below holds only 2 branch-collapsed psum sites
(``_attn_qkv``'s if/else arms are exclusive — they count once, which
is exactly the collapse a naive site count gets wrong). The
per-forward constant term (embed psum + logits all_gather = 2) is
correct, so exactly the A coefficient is flagged.

Expected: 1 finding.
"""

from jax import lax


class Server:
    def __init__(self, cfg, mesh):
        self.cfg = cfg
        self._psums_per_fwd = (
            3 * cfg.num_layers + 2 if mesh is not None else 0
        )


def _attn_qkv(x, shard):
    if shard:
        return lax.psum(x, "model")
    return lax.psum(x * 2, "model")


def _attn_out(x):
    return lax.psum(x, "model")


def _block(x, shard):
    return _attn_out(_attn_qkv(x, shard))


def embed_lookup(tab, ids):
    return lax.psum(tab[ids], "model")


def _replicate_logits(x):
    return lax.all_gather(x, "model")
