"""POSITIVE: one key feeds two draws with no intervening split — the
draws are perfectly correlated."""

import jax


def sample_pair(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # same key, second draw
    return a, b
