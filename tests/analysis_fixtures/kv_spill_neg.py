"""NEGATIVE: the drain-thread spill the paged server ships
(runtime/paged.py::HostKVSpill) — the tick only ENQUEUES device
slices (async dispatch, no transfer), and the blocking host copy
happens on the spill tier's own thread, off the serving hot set."""

import numpy as np


class Server:
    def _tick(self):
        logits, self.pool = self._step(self.pool)
        if self._pressure():
            blk = self._evict_one()
            # Async handoff: device slices go into a bounded queue;
            # nothing here waits on the copy.
            self._spill.offer(self._key(blk), self._tok(blk), self.pool)


class HostKVSpill:
    def _drain_loop(self):
        # Spill tier's own thread: the blocking device->host transfer
        # is the drain thread's whole job, not the tick's.
        while True:
            key, tok, arrays = self._q.get()
            self._store[key] = tuple(np.asarray(a) for a in arrays)
            self._q.task_done()
