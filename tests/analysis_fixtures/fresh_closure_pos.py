"""POSITIVE: jit of a closure created per loop iteration, and the
throwaway jit-then-call form — both defeat jit's function-object
cache."""

import jax


def build_stages(stages):
    fns = []
    for stage in stages:

        def apply(p, x, _s=stage):
            return _s(p, x)

        fns.append(jax.jit(apply))  # fresh closure every iteration
    return fns


def run_once(f, x):
    return jax.jit(f)(x)  # callable dropped after one call
