"""POSITIVE: host-sync-in-hot-loop over the pipeline-parallel stage
handoff — the boundary activation is pulled to the HOST between every
stage dispatch, so each decode round pays S device->host round trips
and no two stages can ever overlap (the handoff blocks on the producer
before the consumer is even enqueued)."""

import numpy as np


class PipelinedServer:
    def _tick(self):
        return self._tick_pp()

    def _tick_pp(self):
        for k in range(self.decode_window):
            for group in self.groups:
                act = group.feed
                for stage in self.stages:
                    out = stage.pp_dispatch(act)
                    # host round trip per stage per round: kills the
                    # async-dispatch overlap the pipeline exists for
                    act = np.asarray(out)
                group.feed = act
        return self.groups
