"""NEGATIVE fixture: shard-spec psum-mirror in sync.

Identical model to psum_mirror_pos.py, mirror corrected to the true
branch-collapsed accounting: 2 per-layer psum sites, 2 per-forward
constants (embed psum + logits all_gather).
"""

from jax import lax


class Server:
    def __init__(self, cfg, mesh):
        self.cfg = cfg
        self._psums_per_fwd = (
            2 * cfg.num_layers + 2 if mesh is not None else 0
        )


def _attn_qkv(x, shard):
    if shard:
        return lax.psum(x, "model")
    return lax.psum(x * 2, "model")


def _attn_out(x):
    return lax.psum(x, "model")


def _block(x, shard):
    return _attn_out(_attn_qkv(x, shard))


def embed_lookup(tab, ids):
    return lax.psum(tab[ids], "model")


def _replicate_logits(x):
    return lax.all_gather(x, "model")
