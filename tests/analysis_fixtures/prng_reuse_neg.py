"""NEGATIVE: split before the second draw; exclusive branch arms each
draw once; a rebind makes a name a fresh key."""

import jax


def sample_pair(key):
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (4,))
    b = jax.random.uniform(kb, (4,))
    return a, b


def sample_branch(key, greedy):
    if greedy:
        return jax.random.normal(key, (4,))
    return jax.random.uniform(key, (4,))


def sample_chain(key):
    x = jax.random.normal(key, (4,))
    key = jax.random.fold_in(key, 1)
    y = jax.random.normal(key, (4,))
    return x, y
