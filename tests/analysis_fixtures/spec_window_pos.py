"""POSITIVE: spec x decode_window composed WRONG — a python per-round
loop inside the tick that pulls each round's proposals and verdicts
to host as it goes, so a W-round window pays O(W) blocking
device->host transfers (and re-dispatches the next round from host
state) instead of running all W draft+verify rounds in ONE jitted
scan and draining ONE batched [B, W, k+1] transfer at the end
(runtime/paged.py::_tick_spec_window)."""

import numpy as np


class Server:
    def _tick(self):
        for r in range(self.decode_window):
            props, preds = self._spec_round(r)
            props_host = np.asarray(props)  # per-round pull
            preds_host = np.asarray(preds)  # and its verdict twin
            self._commit(r, props_host, preds_host)
