"""POSITIVE: a fused decode window done WRONG — the host loops over
the window's sub-steps in python and syncs every iteration, so the
"window" still pays one device->host round trip per token (plus a
blocking scalar pull per window)."""

import numpy as np


class Server:
    def _tick(self):
        for _ in range(self.decode_window):
            nxt = self._advance()
            stream = np.asarray(nxt)  # per-SUB-STEP transfer
            self._push(stream)
        depth = self.pos
        self.deepest = int(depth[0])  # blocking scalar pull

    def _push(self, stream):
        self.out.extend(stream)
