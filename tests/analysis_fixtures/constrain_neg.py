"""NEGATIVE: the constrained-tick shape the runtime actually ships
(runtime/paged.py::_tick, constrain/runtime.py) — the DFA gather,
mask fold and state advance are all device jnp riding the existing
step, and the only new host traffic is the dead-end flag vector
folded into the tick's one batched drain, justified in place."""

import jax.numpy as jnp
import numpy as np


class Server:
    def _tick(self):
        crow = self._ctrans[self._sampler.cid, self._sampler.cstate]
        mask = crow >= 0
        ll = jnp.where(mask, self._forward(), jnp.finfo(jnp.float32).min)
        nxt = jnp.argmax(ll, axis=-1)
        dead = ~mask.any(-1)
        self._sampler.cstate = jnp.take_along_axis(
            crow, nxt[:, None], 1
        )[:, 0]
        # analysis: ignore[host-sync-in-hot-loop] dead-end flags ride
        # the tick's one batched drain transfer, only while a
        # constrained row is live
        dead_host = np.asarray(dead)
        for i, slot in enumerate(self.slots):
            if dead_host[i]:
                slot.fail("constraint dead end")
