"""Budget fixture (healthy): a serving loop whose ``_tick`` feeds
every counter that budgets.toml's contracts account through — the
static half of all three contracts passes over this file. The
tokens-per-dispatch gauge is fed one call away, through ``_drain``,
to pin the reachable-touch BFS (a direct-touch-only check would
wrongly fail that contract)."""


class Metrics:
    def __init__(self, reg):
        self.host_dispatches = reg.counter(
            "defer_host_dispatches_total", "host->device dispatches"
        )
        self.kv_rows_read = reg.counter(
            "defer_kv_rows_read_total", "kv rows read per tick"
        )
        self.tokens_per_dispatch = reg.gauge(
            "defer_tokens_per_dispatch", "tokens delivered per dispatch"
        )


class Server:
    def _drain(self, toks):
        self.obs.tokens_per_dispatch.set(len(toks))
        return toks

    def _tick(self):
        out = self.step_fn(self.state)
        self.obs.host_dispatches.inc()
        self.obs.kv_rows_read.inc(self.rows)
        return self._drain(out)
