"""Budget fixture (regressed): the metrics are still registered, but
``_tick`` stopped feeding all of them — the silent-regression failure
mode the static half exists to catch (bench numbers go stale while
still looking green). Every contract in budgets.toml must produce a
perf-contract finding over this file, with no bench data needed."""


class Metrics:
    def __init__(self, reg):
        self.host_dispatches = reg.counter(
            "defer_host_dispatches_total", "host->device dispatches"
        )
        self.kv_rows_read = reg.counter(
            "defer_kv_rows_read_total", "kv rows read per tick"
        )
        self.tokens_per_dispatch = reg.gauge(
            "defer_tokens_per_dispatch", "tokens delivered per dispatch"
        )


class Server:
    def _tick(self):
        # No counter touches anywhere reachable from here.
        out = self.step_fn(self.state)
        self.state = out
        return out
