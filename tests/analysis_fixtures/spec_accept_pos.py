"""POSITIVE: a speculative accept test done WRONG — the host pulls
the target's prediction and the draft's proposal one SCALAR at a time
inside the per-slot loop, so a k-token round pays O(B * k) blocking
device->host round trips instead of the one batched transfer the
round is designed around (runtime/paged.py::_tick_spec)."""

import numpy as np


class Server:
    def _tick(self):
        props, preds = self._round()
        for i, slot in enumerate(self.slots):
            a = 0
            for j in range(self.spec_k):
                p = int(props[i, j])  # per-proposal scalar pull
                t = np.asarray(preds[i, j])  # and another per token
                if p != t:
                    break
                a += 1
            slot.accept(a)
