"""NEGATIVE fixture: shard-spec.

The same shapes written correctly: specs match the body arity, every
literal axis exists on the literally-constructed mesh, the one
``check_rep=False`` carries its justification ignore, and a dynamic
mesh (``self.mesh``) is skipped rather than guessed at.
"""

from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map


def build(devs):
    mesh = Mesh(devs, ("model",))

    def body(a, b):
        return a + b

    f = shard_map(
        body,
        mesh,
        in_specs=(P("model"), P()),
        out_specs=P("model"),
    )
    g = shard_map(
        lambda a: a * 2,
        mesh,
        in_specs=(P("model"),),
        out_specs=P("model"),
        # analysis: ignore[shard-spec] body ends in a tiled all_gather whose replication the checker cannot infer
        check_rep=False,
    )
    return f, g


class Dynamic:
    def run(self, xs):
        # Mesh held on the instance: axis names are not statically
        # knowable, so the axis check must stay silent here.
        return shard_map(
            lambda a: a,
            self.mesh,
            in_specs=(P("heads"),),
            out_specs=P("heads"),
        )(xs)
