"""NEGATIVE: the stage handoff the pipeline-parallel paged server
ships (runtime/paged.py `_tick_pp`) — boundary activations hop stages
as ASYNC `jax.device_put` futures, so dispatching round k for group g
never waits on any stage's compute; the one device->host copy sits in
the per-window drain behind its justified ignore, exactly like the
monolithic tick. The transport-placed stage worker thread
(runtime/remote_stage.py `serve_pp_stage`) owns its own domain: its
wire framing is a host copy BY DESIGN and carries the justification
inline."""

import threading

import jax
import numpy as np


class PipelinedServer:
    def _tick(self):
        return self._tick_pp()

    def _tick_pp(self):
        for k in range(self.decode_window):
            for group in self.groups:
                act = group.feed
                for stage in self.stages:
                    # async handoff: device_put of a device-resident
                    # future enqueues a copy, never blocks the host
                    act = stage.pp_dispatch(jax.device_put(act, stage.dev))
                group.feed = act
        # analysis: ignore[host-sync-in-hot-loop] ONE batched drain per
        # window, same cadence the monolithic _tick pays
        toks = np.asarray(self._window_tokens())
        return toks

    def _window_tokens(self):
        return self.groups[0].feed


class StageWorker:
    def __init__(self, stage, wire):
        self.stage = stage
        self.wire = wire
        self._thread = threading.Thread(
            target=self._serve, name="pp-stage-worker", daemon=True
        )

    # analysis: domain(pp-stage-worker) the worker thread owns the
    # stage session; the controller only reaches it over the wire
    def _serve(self):
        for bundle in self.wire:
            out = self.stage.pp_dispatch(bundle)
            # analysis: ignore[host-sync-in-hot-loop] framing the
            # result onto the wire IS the stage boundary here
            self.wire.send(np.asarray(out))
