"""POSITIVE: stall-mode admission prefill inside the serving tick —
the prompt runs to completion in its own per-chunk dispatch loop with
a host sync per chunk, so every live decode slot stalls behind
len(chunks) round trips before the tick's decode dispatch even
starts."""

import numpy as np


class Server:
    def _tick(self):
        # Admission-prefill-in-the-tick: each seated prompt is run to
        # completion HERE, serially, before decode advances.
        for seat in self._seats():
            for chunk in self._chunks(seat):
                logits = self.step(self.params, chunk)
                # Per-chunk device->host pull to decide the next
                # chunk's offset — one sync per chunk per prompt.
                seat.pos += int(np.asarray(logits.shape_info)[0])
        feed = self._decode_feed()
        out = self.step(self.params, feed)
        # Per-tick scalar pull on the decode result.
        self.last = out[0, 0].item()
