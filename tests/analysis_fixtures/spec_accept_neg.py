"""NEGATIVE: the accept-test shape the paged speculative round
actually ships (runtime/paged.py::_tick_spec) — ONE batched transfer
of the whole (props, preds) pair per round, justified in place, then
pure host numpy for the per-slot accept lengths. Nothing else
syncs."""

import numpy as np


class Server:
    def _tick(self):
        props, preds = self._round()
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # accept-test transfer per speculative round — up to k+1
        # tokens per slot amortize it
        preds_host = np.asarray(preds)
        # analysis: ignore[host-sync-in-hot-loop] proposal half of the
        # same batched round transfer
        props_host = np.asarray(props)
        mismatch = props_host != preds_host
        first_bad = mismatch.argmax(axis=1)
        a_vec = np.where(
            mismatch.any(axis=1), first_bad, props_host.shape[1]
        )
        for i, slot in enumerate(self.slots):
            slot.accept(a_vec[i])
