"""NEGATIVE: the sanctioned shapes — module-level jit, and a builder
that RETURNS the jitted callable to a memoizing caller (the
utils/memo.cached_step idiom)."""

import jax


@jax.jit
def double(x):
    return x * 2


def build_step(dec):
    def step(p, x):
        return dec.apply(p, x)

    return jax.jit(step)  # caller memoizes; traced once per decoder


class Decoder:
    def generate(self, params, ids):
        step = self._cache.setdefault("step", build_step(self))
        return step(params, ids)
