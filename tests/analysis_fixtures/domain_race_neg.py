"""NEGATIVE fixture: cross-domain-write.

The same two-thread spill shape, written the three sanctioned ways:

  * shared stats mutated under the lock on BOTH sides — cross-domain
    but mediated, so no finding;
  * the payload itself handed off through a queue (park/pump): the
    drain thread only parks, the serving tick pops and does every
    store mutation itself — single writer by construction;
  * a test seam annotated ``domain(any)``: its write never counts
    toward a race, and the serving loop's own write to that slot is
    then single-domain.
"""

import threading


class CleanSpill:
    def __init__(self, q):
        self.q = q
        self.store = {}
        self.stats = 0
        self.fail = None
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._drain_loop, name="spill-drain", daemon=True
        )

    # analysis: domain(drain) parks payloads for the serving thread; store mutation stays on the pump side
    def _drain_loop(self):
        while True:
            item = self.recv()
            self.q.put(item)  # park: a method call, not an attr write
            with self._lock:
                self.stats += 1  # cross-domain but lock-mediated

    def recv(self):
        return ("k", 1)

    def _tick(self):
        item = self.q.get()  # pump: serving thread owns the store
        if item is not None:
            self.store[item[0]] = item[1]
        with self._lock:
            self.stats += 1
        self.fail = None  # only serving writes this concretely
        return len(self.store)

    # analysis: domain(any) test seam — one pointer store, read-and-cleared by the loop
    def inject_failure(self, exc):
        self.fail = exc
