"""NEGATIVE: the lock guards only shared state; blocking calls happen
outside the critical section."""


class Sender:
    def send(self, frame):
        with self._lock:
            self._queue.append(frame)
        self._sock.sendall(frame)

    def stop(self):
        with self._lock:
            self._closing = True
        self._worker.join()
