"""POSITIVE: spill-tier copies issued synchronously inside the
serving tick — evicting a prefix block by blocking on the
device->host transfer stalls every seated request behind one block's
DMA (the exact stall the drain-thread design exists to avoid)."""

import numpy as np


class Server:
    def _tick(self):
        logits, self.pool = self._step(self.pool)
        if self._pressure():
            blk = self._evict_one()
            # Synchronous spill copy ON the tick path: the transfer
            # completes before the next decode step can dispatch.
            self._store[blk] = np.asarray(self.pool[:, blk])
        self._spill_scale(blk)

    def _spill_scale(self, blk):
        # Reachable from _tick: one more blocking pull per eviction.
        self._scales[blk] = self.scale[blk].item()
