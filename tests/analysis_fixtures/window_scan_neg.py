"""NEGATIVE: the fused-window shape the decode servers actually use —
one `lax.scan`-bodied window program dispatched per window, drained
with device slices. The scan body is a nested def passed to lax.scan
by VALUE (never called by name from the hot set), so hot-set
inference must not descend into it, and nothing here syncs."""

import jax
import jax.numpy as jnp
from jax import lax


class Server:
    def _tick(self):
        window = self._build_window()
        cache, toks = window(self.params, self.cache, self.feed)
        self.cache = cache
        for i, slot in enumerate(self.slots):
            # Device slice into the slot's token list — no transfer.
            slot.toks.append(toks[i][None, :])

    def _build_window(self):
        K = self.decode_window
        raw = self.raw_step

        def window(params, cache, feed):
            def body(carry, _):
                cache, feed = carry
                logits, cache = raw(params, cache, feed)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1)
                return (cache, nxt[:, None]), nxt

            (cache, feed), toks = lax.scan(
                body, (cache, feed), None, length=K
            )
            return cache, toks.T

        return jax.jit(window)
