"""POSITIVE fixture: shard-spec.

Three ways a hand-maintained shard_map call drifts from reality:

  * ``in_specs`` arity != the body's positional signature (traces as
    an opaque pytree error at runtime; one line here);
  * a PartitionSpec naming an axis the (literally constructed) mesh
    does not have;
  * ``check_rep=False`` with no justification ignore.

Expected: 3 findings.
"""

from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map


def build(devs):
    mesh = Mesh(devs, ("model",))

    def body(a, b):
        return a + b

    f = shard_map(  # arity: 1 spec for a 2-parameter body
        body,
        mesh,
        in_specs=(P("model"),),
        out_specs=P("model"),
    )
    g = shard_map(
        body,
        mesh,
        in_specs=(P("model"), P("data")),  # "data" is not a mesh axis
        out_specs=P("model"),
        check_rep=False,  # and no ignore says why
    )
    return f, g
