"""NEGATIVE fixture: lock-discipline through one callgraph level.

The fixed shape: the critical section only encodes (pure compute in a
helper) and bumps the sequence number; the blocking ``sendall`` runs
after the lock is released, so no thread stalls behind the I/O.
"""

import threading


class Framer:
    def __init__(self, sock):
        self.sock = sock
        self.seq = 0
        self._lock = threading.Lock()

    def _encode(self, payload):
        return len(payload).to_bytes(4, "big") + payload

    def push(self, payload):
        with self._lock:
            frame = self._encode(payload)  # pure compute: fine
            self.seq += 1
        self.sock.sendall(frame)  # the wait lives outside the lock
