"""POSITIVE: blocking I/O and a thread join while holding a lock —
every other thread touching the lock stalls behind the wait."""


class Sender:
    def send(self, frame):
        with self._lock:
            self._sock.sendall(frame)  # I/O inside the critical section

    def stop(self):
        with self._lock:
            self._worker.join()  # unbounded wait under the lock
