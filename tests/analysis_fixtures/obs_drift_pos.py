"""POSITIVE: three convention breaks — missing defer_ prefix, counter
without _total, non-counter ending in _total."""

from defer_tpu.obs.metrics import get_registry

reg = get_registry()
ticks = reg.counter("serving_ticks_total", "Ticks run")
tx = reg.counter("defer_tx_bytes", "Bytes sent")
depth = reg.gauge("defer_queue_depth_total", "Pending requests")
