"""POSITIVE fixture: cross-domain-write.

A spill-store clone where the drain thread and the serving tick both
write the same instance attribute with no lock and no park/pump
handoff — the single-writer invariant the race detector enforces.
Expected: 2 findings (each unlocked write is flagged against the
other's domain).
"""

import threading


class RacySpill:
    def __init__(self, q):
        self.q = q
        self.store = {}
        self._thread = threading.Thread(
            target=self._drain_loop, name="spill-drain", daemon=True
        )

    # No domain annotation: the Thread site infers domain
    # "spill-drain" from the name= literal.
    def _drain_loop(self):
        while True:
            item = self.q.get()
            self.store[item[0]] = item[1]  # drain-thread write

    def _tick(self):
        # Serving-root write to the same (class, attr) slot, unlocked.
        self.store["hot"] = 1
        return len(self.store)
