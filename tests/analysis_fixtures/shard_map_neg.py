"""NEGATIVE: the sharded tick shape the paged server actually ships —
the shard_map-wrapped body is pure traced jax (psum-reduced, logits
all-gathered in-body), every host transfer stays OUTSIDE at the tick
level behind the sanctioned batched-drain ignore. The wrapper edge
makes `body` hot; nothing inside it syncs."""

import numpy as np
from jax import lax

from defer_tpu.utils.compat import shard_map


class Server:
    def _tick(self):
        step = self._build_step()
        logits, self.pool = step(self.params, self.pool, self.feed)
        # analysis: ignore[host-sync-in-hot-loop] one batched transfer
        # per tick by design — the drain the loop is built around
        toks = np.asarray(logits.argmax(-1))
        self._emit(toks)

    def _build_step(self):
        def body(params, pool, feed):
            x = self._embed(params, feed)
            attn = self._attend(params, pool, x)
            out = lax.psum(attn @ params["wo"], "model")
            return lax.all_gather(out, "model", axis=-1, tiled=True), pool

        return shard_map(
            body, self.mesh,
            in_specs=(None, None, None), out_specs=(None, None),
        )

    def _attend(self, params, pool, x):
        return x @ pool  # local KV shard only; pure device math

    def _emit(self, toks):
        self.out.extend(toks.tolist())
