"""POSITIVE: constrained decoding done WRONG — the host walks the
DFA itself inside the tick's per-slot loop, pulling each slot's
device-resident state down as a scalar and fetching its transition
row to argmax on the host. That is O(B) blocking device->host round
trips per token, where the shipped runtime folds the mask on device
(one gather + one where, constrain/runtime.py) and never reads the
state back."""

import numpy as np


class Server:
    def _tick(self):
        logits = self._forward()
        states = self._sampler.cstate  # device-resident rows
        for i, slot in enumerate(self.slots):
            s = int(states[i])  # per-slot state pull
            row = np.asarray(self._ctrans[slot.cid, s])  # row fetch
            masked = np.where(row >= 0, logits[i], -1e30)
            slot.emit(masked.argmax())
