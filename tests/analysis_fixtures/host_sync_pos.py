"""POSITIVE: host syncs inside the serving hot set — one directly in
the `_tick` root, one in a helper reachable from it."""

import numpy as np


class Server:
    def _tick(self):
        nxt = self._advance()
        toks = np.asarray(nxt)  # per-tick device->host transfer
        self._emit(toks)

    def _emit(self, toks):
        self.out.append(toks.item())  # reachable from _tick
