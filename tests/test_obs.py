"""Metrics core semantics, export formats, and the end-to-end
contract that the serving runtimes report consistent numbers through
the process registry."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.obs import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    PeriodicDumper,
    ServerStats,
    get_registry,
    log_buckets,
)
from defer_tpu.obs import reset as obs_reset


# -- registry / instrument semantics ----------------------------------


def test_counter_gauge_basics():
    r = MetricsRegistry()
    c = r.counter("c_total", "help text")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = r.gauge("g")
    g.set(10)
    g.dec(3)
    g.inc()
    assert g.value == 8
    # Same (name, labels) -> the same instrument object.
    assert r.counter("c_total") is c
    assert r.counter("x", labels={"a": "1"}) is not r.counter(
        "x", labels={"a": "2"}
    )
    # A name cannot change kind.
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("c_total")


def test_counter_thread_safety_exact_count():
    """8 threads x 10k increments must land exactly — int += is not
    atomic under the GIL, the per-instrument lock is load-bearing."""
    r = MetricsRegistry()
    c = r.counter("hammer_total")
    h = r.histogram("hammer_seconds", buckets=[0.5, 1.0])
    n_threads, per = 8, 10_000

    def work():
        for _ in range(per):
            c.inc()
            h.observe(0.75)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per
    assert h.count == n_threads * per
    snap = h._snapshot()
    assert snap["buckets"][1][1] == n_threads * per  # le=1.0 cum


def test_histogram_bucket_edges_le_semantics():
    """Prometheus le semantics: bucket i counts v <= edges[i]; a value
    exactly on an edge lands in that edge's bucket; beyond the last
    edge lands only in +Inf."""
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.1, 0.5, 1.0, 9.9, 10.0, 11.0):
        h.observe(v)
    snap = h._snapshot()
    assert snap["buckets"] == [
        [0.1, 2],       # 0.05, 0.1
        [1.0, 4],       # + 0.5, 1.0
        [10.0, 6],      # + 9.9, 10.0
        ["+Inf", 7],    # + 11.0
    ]
    assert snap["count"] == 7
    assert snap["sum"] == pytest.approx(sum((0.05, 0.1, 0.5, 1.0, 9.9, 10.0, 11.0)))
    # Weighted observe: one bisect, n counts.
    h.observe(0.5, n=3)
    assert h.count == 10
    assert h._snapshot()["buckets"][1][1] == 7


def test_log_buckets_shape_and_validation():
    edges = log_buckets(1e-3, 10.0, 4)
    assert edges == pytest.approx((1e-3, 1e-2, 1e-1, 1.0))
    with pytest.raises(ValueError):
        log_buckets(0.0, 2.0, 4)
    with pytest.raises(ValueError):
        log_buckets(1e-3, 1.0, 4)
    with pytest.raises(ValueError, match="ascending"):
        MetricsRegistry().histogram("h", buckets=[2.0, 1.0])


def test_reset_zeroes_in_place_keeping_handles():
    """reset() must zero values WITHOUT replacing instruments: hot
    paths cache handles at construction, and a swapped object would
    silently orphan them (the test-isolation contract)."""
    r = MetricsRegistry()
    c = r.counter("c_total")
    h = r.histogram("h_seconds", buckets=[1.0])
    c.inc(7)
    h.observe(0.5)
    r.reset()
    assert c.value == 0 and h.count == 0 and h.sum == 0.0
    assert r.counter("c_total") is c  # same object survives
    c.inc()  # the cached handle still feeds the registry
    assert r.value("c_total") == 1


def test_quantile_estimate():
    r = MetricsRegistry()
    h = r.histogram("q", buckets=[1.0, 2.0, 4.0])
    assert h.approx_quantile(0.5) is None
    for _ in range(100):
        h.observe(1.5)
    q = h.approx_quantile(0.5)
    assert 1.0 <= q <= 2.0


# -- export sinks -----------------------------------------------------


def test_prometheus_exposition_golden():
    """Pin the exact text exposition: HELP/TYPE headers, sorted label
    rendering, cumulative buckets with a trailing +Inf, _sum/_count."""
    r = MetricsRegistry()
    r.counter(
        "defer_requests_total", "Requests served", {"server": "flat"}
    ).inc(3)
    r.gauge("defer_pool_blocks_free", "Free blocks").set(5)
    h = r.histogram(
        "defer_ttft_seconds", "Time to first token", buckets=[0.1, 1.0]
    )
    # Powers of two: the _sum accumulates exactly, so the golden
    # string can pin it without float-formatting slack.
    h.observe(0.0625)
    h.observe(0.5)
    h.observe(2.0)
    golden = (
        '# HELP defer_pool_blocks_free Free blocks\n'
        '# TYPE defer_pool_blocks_free gauge\n'
        'defer_pool_blocks_free 5\n'
        '# HELP defer_requests_total Requests served\n'
        '# TYPE defer_requests_total counter\n'
        'defer_requests_total{server="flat"} 3\n'
        '# HELP defer_ttft_seconds Time to first token\n'
        '# TYPE defer_ttft_seconds histogram\n'
        'defer_ttft_seconds_bucket{le="0.1"} 1\n'
        'defer_ttft_seconds_bucket{le="1"} 2\n'
        'defer_ttft_seconds_bucket{le="+Inf"} 3\n'
        'defer_ttft_seconds_sum 2.5625\n'
        'defer_ttft_seconds_count 3\n'
    )
    assert r.to_prometheus() == golden


def test_to_dict_json_round_trip():
    r = MetricsRegistry()
    r.counter("a_total", labels={"k": "v"}).inc(2)
    r.histogram("b_seconds", buckets=[1.0]).observe(0.5)
    d = json.loads(json.dumps(r.to_dict()))
    assert d["counters"]['a_total{k="v"}'] == 2
    assert d["histograms"]["b_seconds"]["count"] == 1


def test_periodic_dumper_writes_file(tmp_path):
    r = MetricsRegistry()
    r.counter("dump_total").inc(9)
    path = tmp_path / "metrics.jsonl"
    d = PeriodicDumper(r, interval_s=60.0, path=str(path), fmt="json")
    d.dump_once()
    line = path.read_text().strip()
    assert json.loads(line)["counters"]["dump_total"] == 9
    with pytest.raises(ValueError, match="json|prometheus"):
        PeriodicDumper(r, fmt="xml")


def test_server_stats_dict_and_attr_access():
    s = ServerStats({"ticks": 4})
    assert s["ticks"] == 4 and s.ticks == 4
    s.extra = 1
    assert s["extra"] == 1
    with pytest.raises(AttributeError):
        s.missing
    assert isinstance(s, dict)  # legacy **stats / [key] call sites


# -- end-to-end: the serving runtimes report through the registry -----


def test_flat_server_metrics_consistency():
    """A small DecodeServer run must report: admitted == finished ==
    requests, tokens_generated == sum(step budgets), TTFT observations
    == admissions, and the ticks counter == the server's own tick
    count."""
    from defer_tpu.runtime.decode_server import serve_greedy

    obs_reset()
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = [
        (jnp.asarray([[3, 9, 27]], jnp.int32), 7),
        (jnp.asarray([[5]], jnp.int32), 4),
        (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32), 9),
    ]
    outs, stats = serve_greedy(dec, params, reqs, max_batch=2)
    reg = get_registry()
    lab = {"server": "flat"}
    assert reg.value("defer_requests_admitted_total", **lab) == len(reqs)
    assert reg.value("defer_requests_finished_total", **lab) == len(reqs)
    assert reg.value("defer_tokens_generated_total", **lab) == sum(
        s for _, s in reqs
    )
    assert reg.value("defer_prefill_tokens_total", **lab) == sum(
        p.shape[1] for p, _ in reqs
    )
    assert reg.value("defer_decode_ticks_total", **lab) == stats["ticks"]
    ttft = reg.value("defer_ttft_seconds", **lab)
    assert ttft["count"] == len(reqs)
    qw = reg.value("defer_queue_wait_seconds", **lab)
    assert qw["count"] == len(reqs)
    # The snapshot rides the stats return-channel too.
    snap = stats.metrics["counters"]
    assert snap['defer_tokens_generated_total{server="flat"}'] == sum(
        s for _, s in reqs
    )
    # Exposition renders the whole serving family without error.
    text = reg.to_prometheus()
    assert 'defer_ttft_seconds_bucket{le="+Inf",server="flat"}' in text


def test_paged_server_metrics_and_prefix_cache_counters():
    """Paged run with the radix cache: hit/miss counters must be
    consistent with the sharing scenario (first admission all misses,
    identical second prompt all hits), pool gauges must reconcile with
    the free list, and token/TTFT counts mirror the flat contract."""
    from defer_tpu.runtime.paged import serve_paged

    obs_reset()
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    bs = 4
    prompt = jnp.asarray([[7, 3, 1, 12, 9, 2, 4, 4, 11]], jnp.int32)
    reqs = [(prompt, 5), (prompt, 5)]
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=24, block_size=bs,
        max_batch=1, prefix_cache=True,
    )
    reg = get_registry()
    lab = {"server": "paged"}
    n_full = prompt.shape[1] // bs  # 2 full prompt blocks
    # Request 1: n_full misses; request 2 (same prompt, serialized by
    # max_batch=1): n_full hits against request 1's parked blocks.
    assert reg.value("defer_prefix_cache_misses_total", **lab) == n_full
    assert reg.value("defer_prefix_cache_hits_total", **lab) == n_full
    # Finishing parked each request's shared blocks at refcount 0;
    # request 2 revived request 1's parked blocks.
    assert reg.value("defer_prefix_cache_revivals_total", **lab) == n_full
    assert reg.value("defer_prefix_cache_parks_total", **lab) == 2 * n_full
    assert reg.value("defer_prefix_cache_evictions_total", **lab) == 0
    assert reg.value("defer_requests_admitted_total", **lab) == 2
    assert reg.value("defer_requests_finished_total", **lab) == 2
    assert reg.value("defer_tokens_generated_total", **lab) == 10
    assert reg.value("defer_ttft_seconds", **lab)["count"] == 2
    # Cached-prefix prefill skip shows up as fewer prefill tokens on
    # the second admission (only the suffix runs).
    assert (
        reg.value("defer_prefill_tokens_total", **lab)
        == 2 * prompt.shape[1] - stats["prefill_tokens_saved"]
    )
    # Pool gauges: all requests done, so nothing is held by slots.
    assert reg.value("defer_pool_blocks_used", **lab) == 0
    assert stats["cached_blocks"] == n_full
    # Both outputs identical (same prompt, greedy).
    np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(outs[1]))


def test_dispatch_efficiency_metrics():
    """The fused-window instruments (runtime/*.py `decode_window`):
    at K=1, defer_host_dispatches_total mirrors the tick counter and
    nothing truncates; at K>1, dispatches collapse by ~K while the
    token counters stay request-exact; an eos mid-window trips
    defer_window_truncated_total."""
    from defer_tpu.runtime.decode_server import serve_greedy

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = [
        (jnp.asarray([[3, 9, 27]], jnp.int32), 13),
        (jnp.asarray([[5]], jnp.int32), 11),
        (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32), 12),
    ]
    lab = {"server": "flat"}
    reg = get_registry()
    obs_reset()
    outs, st1 = serve_greedy(dec, params, reqs, max_batch=2)
    assert st1["decode_window"] == 1
    assert st1["host_dispatches"] == st1["ticks"]
    assert (
        reg.value("defer_host_dispatches_total", **lab)
        == reg.value("defer_decode_ticks_total", **lab)
        == st1["ticks"]
    )
    assert reg.value("defer_window_truncated_total", **lab) == 0
    assert reg.value("defer_tokens_per_dispatch", **lab) >= 1

    obs_reset()
    outs4, st4 = serve_greedy(
        dec, params, reqs, max_batch=2, decode_window=4
    )
    for a, b in zip(outs, outs4):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert st4["decode_window"] == 4
    assert st4["host_dispatches"] < st1["host_dispatches"]
    assert (
        reg.value("defer_host_dispatches_total", **lab)
        == st4["host_dispatches"]
    )
    # Window-exact tokens: however the budgets are windowed, the
    # accepted total equals the requested step budgets.
    assert reg.value("defer_tokens_generated_total", **lab) == sum(
        s for _, s in reqs
    )
    assert st4["tokens_per_dispatch"] > 1.0

    # eos mid-window: pick a token actually generated mid-stream and
    # re-serve with it — deterministic truncation on a cut window.
    # Index 3, not earlier: greedy tiny_gpt repeats its first token
    # for a few steps, and an eos equal to a request's FIRST token
    # finishes it at admission, before any window runs.
    t0 = reqs[0][0].shape[1]
    eos = int(np.asarray(outs[0])[0, t0 + 3])
    obs_reset()
    _, _ = serve_greedy(
        dec, params, reqs, max_batch=2, decode_window=4, eos_id=eos
    )
    assert reg.value("defer_window_truncated_total", **lab) > 0


def test_batch_gatherer_flush_reason_counters():
    """BatchGatherer flush accounting: a filled batch counts as
    "full", an SLO expiry as "timeout", a sentinel as "eos", an
    incompatible item as "mismatch"; occupancy lands in the rows
    histogram."""
    import queue

    from defer_tpu.runtime.batching import BatchGatherer
    from defer_tpu.runtime.host_io import STOP

    obs_reset()
    reg = get_registry()
    g = BatchGatherer(4, max_wait_s=0.02)
    q: "queue.Queue" = queue.Queue()

    # full: two 2-row items fill batch_size=4.
    q.put(np.zeros((2, 3), np.float32))
    q.put(np.zeros((2, 3), np.float32))
    batch, sizes, eos = g.gather(q)
    assert batch.shape[0] == 4 and not eos
    assert reg.value("defer_batch_flush_total", reason="full") == 1

    # timeout: one item, SLO expires.
    q.put(np.zeros((1, 3), np.float32))
    batch, sizes, eos = g.gather(q)
    assert sizes == [1] and not eos
    assert reg.value("defer_batch_flush_total", reason="timeout") == 1

    # mismatch: trailing-shape change flushes, odd item carries.
    q.put(np.zeros((1, 3), np.float32))
    q.put(np.zeros((1, 5), np.float32))
    g.gather(q)
    assert reg.value("defer_batch_flush_total", reason="mismatch") == 1
    assert g.pending()

    # eos: carried item flushes against the sentinel.
    q.put(STOP)
    batch, sizes, eos = g.gather(q)
    assert eos
    assert reg.value("defer_batch_flush_total", reason="eos") == 1

    rows = reg.value("defer_batch_rows")
    assert rows["count"] == 4  # one observation per flush


def test_codec_byte_counters_and_q8_no_double_count():
    """encode() books raw vs frame bytes once per public call — the
    Q8 path's inner lossless encode must NOT double-count."""
    from defer_tpu.runtime import codec

    obs_reset()
    reg = get_registry()
    a = np.linspace(-1, 1, 4096).astype(np.float32).reshape(64, 64)
    f1 = codec.encode(a, level=3)
    assert reg.value("defer_codec_raw_bytes_total") == a.nbytes
    assert reg.value("defer_codec_encoded_bytes_total") == len(f1)
    obs_reset()
    f2 = codec.encode(a, level=3, quantize="int8")
    # Exactly the original float bytes, not float + inner int8.
    assert reg.value("defer_codec_raw_bytes_total") == a.nbytes
    assert reg.value("defer_codec_encoded_bytes_total") == len(f2)
    np.testing.assert_allclose(
        codec.decode(f2), a, atol=2.0 / 127.0
    )


def test_disagg_metrics_names_and_serving_integration():
    """DisaggMetrics registers the disagg instrument family under the
    role label, serve_disagg drives them, and every name passes the
    obs-name-drift conventions (counters end _total, etc. — the
    analysis lint pins the same rules statically)."""
    from defer_tpu.obs import DisaggMetrics
    from defer_tpu.disagg import serve_disagg

    obs_reset()
    m = DisaggMetrics("prefill")
    snap = m.registry.to_dict()
    flat = {**snap["counters"], **snap["histograms"]}
    for name in (
        'defer_kv_blocks_shipped_total{role="prefill"}',
        'defer_kv_block_bytes_sent_total{role="prefill"}',
        'defer_kv_block_bytes_recv_total{role="prefill"}',
        'defer_kv_ingest_wait_seconds{role="prefill"}',
        'defer_disagg_worker_restarts_total{role="prefill"}',
    ):
        assert name in flat, name

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = [(jnp.asarray([[3, 9, 27, 1, 4]], jnp.int32), 4)]
    _, stats = serve_disagg(
        dec, params, reqs, num_blocks=8, block_size=4, max_batch=2
    )
    reg = m.registry
    shipped = reg.value(
        "defer_kv_blocks_shipped_total", role="prefill"
    )
    assert shipped == 2  # ceil(5 / 4) blocks for the one request
    sent = reg.value("defer_kv_block_bytes_sent_total", role="prefill")
    recvd = reg.value("defer_kv_block_bytes_recv_total", role="decode")
    assert sent == recvd == stats["kv_bytes_recv"] > 0
    # the payload waited in the ingest queue at least once
    hist = reg.value("defer_kv_ingest_wait_seconds", role="decode")
    assert hist["count"] == 1
