"""IR wire serialization + the remote stage worker (the reference's
ship-a-submodel-to-another-process deployment, reference
src/dispatcher.py:47-88 / src/node.py:135-152)."""

import json
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from defer_tpu.graph.ir import GraphError
from defer_tpu.graph.partition import partition, stage_params
from defer_tpu.graph.serialize import (
    frames_to_params,
    graph_from_json,
    graph_to_json,
    params_to_frames,
)
from defer_tpu.models import get_model
from tests.test_partition import residual_chain


def test_graph_json_round_trip_resnet50():
    g = get_model("resnet50").graph
    g2 = graph_from_json(graph_to_json(g))
    assert g2.name == g.name
    assert g2.input_name == g.input_name
    assert g2.output_name == g.output_name
    assert len(g2.nodes) == len(g.nodes)
    for a, b in zip(g.nodes, g2.nodes):
        assert (a.name, a.op, a.inputs) == (b.name, b.op, b.inputs)
        assert dict(a.attrs) == dict(b.attrs)


def test_graph_json_round_trip_applies_identically():
    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    x = jax.random.normal(jax.random.key(1), (2, 8))
    g2 = graph_from_json(graph_to_json(g))
    np.testing.assert_allclose(
        np.asarray(g2.apply(params, x)),
        np.asarray(g.apply(params, x)),
        rtol=1e-6,
    )


def test_stage_graph_round_trip_with_bundles():
    from defer_tpu.graph.ir import GraphBuilder

    gb = GraphBuilder("skip")
    v = gb.input()
    h_prev = gb.add("dense", v, name="h0", features=16)
    h = gb.add("dense", h_prev, name="h1", features=16)
    for i in range(2, 5):
        nxt = gb.add("add", h, h_prev, name=f"mix{i}")
        nxt = gb.add("dense", nxt, name=f"h{i}", features=16)
        h_prev, h = h, nxt
    g = gb.build(gb.add("dense", h, name="head", features=4))
    stages = partition(g, [("h2", "h1")])
    st1 = stages[1]
    st1b = graph_from_json(graph_to_json(st1))
    assert st1b.input_names == st1.input_names
    assert st1b.output_names == st1.output_names
    params = g.init(jax.random.key(0), (2, 16))
    sp = stage_params(params, st1)
    acts = (jnp.ones((2, 16)), jnp.ones((2, 16)) * 2)
    np.testing.assert_allclose(
        np.asarray(st1b.apply(sp, acts)),
        np.asarray(st1.apply(sp, acts)),
        rtol=1e-6,
    )


def test_params_frames_round_trip():
    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    pairs = params_to_frames(params)
    back = frames_to_params(pairs)
    # Parameterless nodes need no wire frames (apply uses
    # params.get(name, {})); every parameterized node round-trips.
    want = {k: dict(v) for k, v in params.items() if v}
    assert jax.tree_util.tree_structure(back) == jax.tree_util.tree_structure(
        want
    )
    for (p1, a1), (p2, a2) in zip(pairs, params_to_frames(back)):
        assert p1 == p2
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    x = jax.random.normal(jax.random.key(1), (2, 8))
    np.testing.assert_allclose(
        np.asarray(g.apply(back, x)), np.asarray(g.apply(params, x)),
        rtol=1e-6,
    )


def test_graph_json_rejects_malformed():
    with pytest.raises(GraphError, match="not a graph"):
        graph_from_json("{]")
    with pytest.raises(GraphError, match="not a graph"):
        graph_from_json(json.dumps({"no": "nodes"}))
    with pytest.raises(GraphError, match="wire version"):
        graph_from_json(
            json.dumps({"wire_version": 99, "nodes": [], "name": "x"})
        )
    doc = json.loads(graph_to_json(residual_chain()))
    del doc["nodes"][0]["op"]
    with pytest.raises(GraphError, match="malformed"):
        graph_from_json(json.dumps(doc))


def test_two_process_pipeline_over_the_wire():
    """The reference's deployment, end to end across OS processes:
    parent partitions, ships stage 1 (JSON + weights) to a child
    process, streams activations, and collects relayed results equal to
    the single-program forward."""
    from defer_tpu.runtime.remote_stage import (
        dispatch_stage,
        recv_results,
        send_activation,
    )
    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    st0, st1 = partition(g, ["add_1"])

    results = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=60.0)
    child = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "defer_tpu.runtime.remote_stage",
            "--listen",
            "0",
            "--next",
            f"127.0.0.1:{results.port}",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
        },
    )
    try:
        line = child.stdout.readline()
        assert line.startswith("LISTENING "), (line, child.stderr.read())
        port = int(line.split()[1])

        send = ArraySender("127.0.0.1", port)
        dispatch_stage(send, st1, stage_params(params, st1))

        got = []
        t = threading.Thread(
            target=lambda: got.extend(recv_results(results)), daemon=True
        )
        t.start()

        n = 5
        p0 = stage_params(params, st0)
        xs = [
            np.random.default_rng(i).standard_normal((2, 8)).astype(
                np.float32
            )
            for i in range(n)
        ]
        for x in xs:
            send_activation(send, st0.apply(p0, x))
        send.close()
        t.join(timeout=120)
        assert not t.is_alive() and len(got) == n
        for x, out in zip(xs, got):
            np.testing.assert_allclose(
                out, np.asarray(g.apply(params, x)), rtol=1e-4, atol=1e-6
            )
        assert child.wait(timeout=60) == 0
        assert "DONE 5" in child.stdout.read() + line
    finally:
        child.kill()
        results.close()


def test_dispatch_stage_forces_lossless_weights():
    """A sender in int8 activation-quantize mode must NOT quantize the
    weights it dispatches."""
    from defer_tpu.runtime.remote_stage import dispatch_stage
    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    st0, _ = partition(g, ["add_1"])
    sp = stage_params(params, st0)

    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=30.0)
    got = []
    t = threading.Thread(target=lambda: got.extend(recv), daemon=True)
    t.start()
    snd = ArraySender("127.0.0.1", recv.port, quantize="int8")
    dispatch_stage(snd, st0, sp)
    assert snd.quantize == "int8"  # mode restored after dispatch
    snd.close()
    t.join(timeout=30)
    assert not t.is_alive()
    from defer_tpu.graph.serialize import params_to_frames

    pairs = params_to_frames(sp)
    weight_frames = got[2 : 2 + len(pairs)]
    for (_, want), arr in zip(pairs, weight_frames):
        np.testing.assert_array_equal(np.asarray(arr), np.asarray(want))


def test_params_frames_reject_slash_in_param_name():
    with pytest.raises(GraphError, match="'/'"):
        params_to_frames({"node": {"a/b": np.zeros(2)}})


def test_worker_truncated_dispatch_errors_cleanly():
    """Peer closing mid-dispatch (after the manifest, before all weight
    frames) must produce the diagnostic error, not PEP 479's opaque
    'generator raised StopIteration'."""
    from defer_tpu.graph.serialize import graph_to_json, params_to_frames
    from defer_tpu.runtime.remote_stage import serve_stage
    from defer_tpu.runtime.transport import ArraySender

    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    st0, _ = partition(g, ["add_1"])
    sp = stage_params(params, st0)

    port_box = {}
    errors = []

    def worker():
        try:
            serve_stage(
                0,
                "127.0.0.1",
                1,  # never reached: dispatch fails first
                listen_host="127.0.0.1",
                accept_timeout_s=30.0,
                announce=lambda p: port_box.setdefault("port", p),
            )
        except Exception as e:  # noqa: BLE001 — the assertion target
            errors.append(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    deadline = 50
    while "port" not in port_box and not errors and deadline:
        threading.Event().wait(0.1)
        deadline -= 1
    assert not errors, f"worker failed before announcing: {errors[0]!r}"
    snd = ArraySender("127.0.0.1", port_box["port"])
    pairs = params_to_frames(sp)
    snd.send(np.frombuffer(graph_to_json(st0).encode(), np.uint8))
    snd.send(
        np.frombuffer(
            json.dumps([p for p, _ in pairs]).encode(), np.uint8
        )
    )
    snd.send(np.asarray(pairs[0][1]))  # only 1 of N weight frames
    snd.close()
    t.join(timeout=60)
    assert not t.is_alive()
    assert errors, "worker should have errored on truncated dispatch"
    assert "before the stage was fully dispatched" in str(errors[0])


def test_three_process_two_worker_chain():
    """The reference's full deployment shape: dispatcher + TWO compute
    nodes chained by --next (reference src/dispatcher.py:54-58), each
    in its own OS process. Each worker is dispatched its stage
    directly; the downstream worker then takes its activation stream
    as a second peer (session handoff, ArrayReceiver.next_peer)."""
    import os

    from defer_tpu.runtime.remote_stage import (
        dispatch_stage,
        recv_results,
        send_activation,
    )
    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    st0, st1, st2 = partition(g, ["add_1", "add_2"])

    results = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=60.0)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def spawn(next_hop: str, *extra: str):
        return subprocess.Popen(
            [
                sys.executable, "-m", "defer_tpu.runtime.remote_stage",
                "--listen", "0", "--next", next_hop, *extra,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env,
        )

    # w2 is mid-chain: --expect-peer makes a missing upstream hop a
    # hard error instead of a silent DONE 0.
    w2 = spawn(f"127.0.0.1:{results.port}", "--expect-peer")
    try:
        line2 = w2.stdout.readline()
        assert line2.startswith("LISTENING "), (line2, w2.stderr.read())
        port2 = int(line2.split()[1])

        # Dispatch w2 directly, then close: its activations will come
        # from w1 as a second peer.
        snd2 = ArraySender("127.0.0.1", port2)
        dispatch_stage(snd2, st2, stage_params(params, st2))
        snd2.close()

        w1 = spawn(f"127.0.0.1:{port2}")
        try:
            line1 = w1.stdout.readline()
            assert line1.startswith("LISTENING "), (line1, w1.stderr.read())
            port1 = int(line1.split()[1])

            snd1 = ArraySender("127.0.0.1", port1)
            dispatch_stage(snd1, st1, stage_params(params, st1))

            got = []
            t = threading.Thread(
                target=lambda: got.extend(recv_results(results)),
                daemon=True,
            )
            t.start()

            n = 4
            p0 = stage_params(params, st0)
            xs = [
                np.random.default_rng(i).standard_normal((2, 8)).astype(
                    np.float32
                )
                for i in range(n)
            ]
            for x in xs:
                send_activation(snd1, st0.apply(p0, x))
            snd1.close()
            t.join(timeout=120)
            assert not t.is_alive() and len(got) == n
            for x, out in zip(xs, got):
                np.testing.assert_allclose(
                    out, np.asarray(g.apply(params, x)),
                    rtol=1e-4, atol=1e-6,
                )
            assert w1.wait(timeout=60) == 0
            assert w2.wait(timeout=60) == 0
        finally:
            w1.kill()
    finally:
        w2.kill()
        results.close()


def test_dispatch_only_session_exits_cleanly_and_fast():
    """Dispatch + close with zero activations: the worker waits only
    the short handoff budget for a phantom chain hop, then exits
    cleanly with zero relayed microbatches."""
    import time

    from defer_tpu.runtime.remote_stage import dispatch_stage, serve_stage
    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    st0, _ = partition(g, ["add_1"])

    sink = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=30.0)
    port_box = {}
    out_box = {}

    def worker():
        out_box["count"] = serve_stage(
            0,
            "127.0.0.1",
            sink.port,
            listen_host="127.0.0.1",
            accept_timeout_s=30.0,
            handoff_timeout_s=2.0,
            announce=lambda p: port_box.setdefault("port", p),
        )

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    deadline = 50
    while "port" not in port_box and deadline:
        threading.Event().wait(0.1)
        deadline -= 1
    snd = ArraySender("127.0.0.1", port_box["port"])
    dispatch_stage(snd, st0, stage_params(params, st0))
    t0 = time.monotonic()
    snd.close()
    t.join(timeout=30)
    sink.close()
    assert not t.is_alive()
    assert out_box["count"] == 0
    assert time.monotonic() - t0 < 10  # handoff budget, not 120s


def test_expected_peer_missing_is_hard_error():
    """A worker declared mid-chain (expect_activation_peer=True) whose
    upstream hop never connects must FAIL, not exit cleanly with zero
    work — the dispatcher cannot otherwise tell a dead chain from a
    successful empty one (ADVICE r03)."""
    from defer_tpu.runtime.remote_stage import dispatch_stage, serve_stage
    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    st0, _ = partition(g, ["add_1"])

    sink = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=30.0)
    port_box = {}
    err_box = {}

    def worker():
        try:
            serve_stage(
                0,
                "127.0.0.1",
                sink.port,
                listen_host="127.0.0.1",
                accept_timeout_s=30.0,
                handoff_timeout_s=2.0,
                expect_activation_peer=True,
                announce=lambda p: port_box.setdefault("port", p),
            )
        except RuntimeError as e:
            err_box["err"] = e

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    deadline = 50
    while "port" not in port_box and deadline:
        threading.Event().wait(0.1)
        deadline -= 1
    snd = ArraySender("127.0.0.1", port_box["port"])
    dispatch_stage(snd, st0, stage_params(params, st0))
    snd.close()
    t.join(timeout=30)
    sink.close()
    assert not t.is_alive()
    assert "expected an upstream activation peer" in str(err_box["err"])
