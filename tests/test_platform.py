"""Shared bounded-backend-init helpers (utils/platform.py)."""

import threading

import jax
import pytest

from defer_tpu.utils.platform import (
    BackendInitHang,
    devices_with_deadline,
    honor_env_platform,
)


def test_devices_with_deadline_passes_through():
    devs = devices_with_deadline(30.0)
    assert devs == jax.devices()


def test_devices_with_deadline_raises_on_hang(monkeypatch):
    """A backend whose init never returns must surface BackendInitHang
    at the deadline, not block the caller forever."""
    release = threading.Event()

    def hang():
        release.wait(30.0)
        return []

    monkeypatch.setattr(jax, "devices", hang)
    try:
        with pytest.raises(BackendInitHang, match="did not complete"):
            devices_with_deadline(0.3)
    finally:
        release.set()  # unblock the probe thread promptly


def test_devices_with_deadline_relays_init_errors(monkeypatch):
    def boom():
        raise RuntimeError("no backend for you")

    monkeypatch.setattr(jax, "devices", boom)
    with pytest.raises(RuntimeError, match="no backend for you"):
        devices_with_deadline(5.0)


def test_honor_env_platform(monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: calls.append((k, v))
    )
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    honor_env_platform()
    assert calls == []
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    honor_env_platform()
    assert calls == [("jax_platforms", "cpu")]
