"""Weight-only int8 decode serving (models/quant.py): quantization
error bounds, end-to-end decode fidelity, and tensor-parallel parity
(int8 trees shard like their float counterparts)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.llama import tiny_llama
from defer_tpu.models.quant import (
    dequantize_leaf,
    quantization_error,
    quantize_decoder_params,
    quantize_leaf,
)


def test_quantize_leaf_round_trip_bound():
    w = jax.random.normal(jax.random.key(0), (64, 128))
    # Symmetric per-channel int8: reconstruction is within one step
    # of the per-channel scale.
    leaf = quantize_leaf(w)
    assert leaf["q"].dtype == jnp.int8
    assert leaf["s"].shape == (1, 128)
    back = dequantize_leaf(leaf, jnp.float32)
    step = np.asarray(leaf["s"])
    assert (np.abs(np.asarray(back - w)) <= step * 0.5 + 1e-7).all()
    assert quantization_error(w) < 1 / 127


def test_quantize_leaf_layer_stacked():
    w = jax.random.normal(jax.random.key(1), (3, 16, 32))
    leaf = quantize_leaf(w)
    assert leaf["q"].shape == (3, 16, 32)
    # Per-layer scales, L leading: lax.scan slices q and s together.
    assert leaf["s"].shape == (3, 1, 32)
    a = dequantize_leaf(
        {"q": leaf["q"][1], "s": leaf["s"][1]}, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(a),
        np.asarray(dequantize_leaf(leaf, jnp.float32)[1]),
        rtol=1e-6,
    )


def test_int8_decode_tracks_full_precision():
    """Quantized llama decode must stay close to the full-precision
    logits (cosine > 0.99) and produce a valid generation."""
    dec = tiny_llama()
    params = dec.init(jax.random.key(0))
    qparams = quantize_decoder_params(params)
    assert qparams["stack"]["wq"]["q"].dtype == jnp.int8
    assert qparams["stack"]["ln1_scale"].dtype != jnp.int8  # untouched

    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, dec.cfg.vocab_size)
    full = np.asarray(dec.reference_logits(params, ids)).reshape(-1)
    quant = np.asarray(dec.reference_logits(qparams, ids)).reshape(-1)
    cos = float(
        np.dot(full, quant)
        / (np.linalg.norm(full) * np.linalg.norm(quant) + 1e-12)
    )
    assert cos > 0.99, f"cosine {cos}"

    out = dec.generate(qparams, jnp.zeros((1, 3), jnp.int32), 4)
    assert out.shape == (1, 7)
    assert (np.asarray(out) >= 0).all()


def test_int8_decode_under_tp_matches_single_device(devices):
    """int8 trees shard like their float counterparts (q takes the
    weight's spec, scales replicate their size-1 axes; vocab-padded
    int8 table): tp=2 quantized decode produces the single-device
    quantized tokens."""
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config, spmd_llama
    from defer_tpu.parallel.mesh import make_mesh

    cfg = llama_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=97,  # exercises the padded int8 table
        max_len=16,
    )
    single = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = single.init(jax.random.key(0))
    qparams = quantize_decoder_params(params)
    prompt = jnp.zeros((1, 3), jnp.int32)
    want = single.generate(qparams, prompt, 5)

    mesh = make_mesh({"model": 2}, devices[:2])
    dec = spmd_llama(mesh, cfg, compute_dtype=jnp.float32)
    sharded = dec.shard_params(quantize_decoder_params(params))
    assert sharded["token_embedding"]["q"].shape == (98, 64)  # padded
    wq = sharded["stack"]["wq"]["q"]
    assert {s.data.shape for s in wq.addressable_shards} == {(2, 64, 32)}
    got = dec.generate(sharded, prompt, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
