"""Two-process `jax.distributed` smoke test on CPU.

multihost.initialize exists to bootstrap real multi-process jobs (the
reference wires peers by hand-listed IPs, reference src/test.py:20);
here two actual processes join a localhost coordinator, build a
DCN-aware mesh spanning both, and run one psum across them — the
minimal end-to-end proof the bootstrap + mesh layout work for their
purpose, not just in single-process no-op mode.
"""

import os
import socket
import subprocess
import sys

_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")

from jax.sharding import NamedSharding, PartitionSpec as P
import jax.numpy as jnp
import numpy as np

from defer_tpu.parallel import multihost

pid, port = int(sys.argv[1]), sys.argv[2]
topo = multihost.initialize(f"localhost:{port}", 2, pid)
assert topo["process_count"] == 2, topo
assert topo["process_index"] == pid, topo
assert jax.device_count() == 4, jax.devices()  # 2 local x 2 processes

mesh = multihost.make_multihost_mesh({"data": 2, "model": 2})
# DCN-aware layout: the data axis must be outermost (spans processes).
assert tuple(mesh.axis_names) == ("data", "model"), mesh.axis_names

sh = NamedSharding(mesh, P("data"))
garr = jax.make_array_from_callback(
    (4,), sh, lambda idx: np.arange(4.0, dtype=np.float32)[idx]
)

def total(x):
    return jax.shard_map(
        lambda a: jax.lax.psum(a, "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P(),
    )(x)

out = jax.jit(total, out_shardings=NamedSharding(mesh, P()))(garr)
# psum over the cross-process data axis sums the halves [0,1]+[2,3].
np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
print(f"proc {pid} OK", flush=True)
"""


def test_two_process_psum(tmp_path):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), str(port)],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} OK" in out
