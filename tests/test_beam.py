"""Beam search: beam 1 == greedy, wider beams never score worse."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.beam import beam_search
from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.models.llama import tiny_llama


def _greedy_score(dec, params, ids, t0):
    """Sum log-prob the model assigns to the generated suffix."""
    logits = dec.reference_logits(params, ids[:, :-1])
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    tot = 0.0
    for t in range(t0, ids.shape[1]):
        tot += float(logp[0, t - 1, int(ids[0, t])])
    return tot


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_beam1_equals_greedy(family):
    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    prompt = jnp.asarray([[3, 7, 1]], jnp.int32)
    want = dec.generate(params, prompt, 8)
    got, scores = beam_search(dec, params, prompt, 8, beam_size=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert scores.shape == (1,)


def test_wider_beam_never_scores_worse():
    """The best beam's sum log-prob must be >= the greedy path's (the
    greedy path is in the search space of every beam width)."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    prompt = jnp.asarray([[5, 2]], jnp.int32)
    steps = 10
    greedy = dec.generate(params, prompt, steps)
    g_score = _greedy_score(dec, params, greedy, 2)
    ids, scores = beam_search(dec, params, prompt, steps, beam_size=4)
    assert float(scores[0]) >= g_score - 1e-4
    # Scores are self-consistent: recompute the winner's path prob.
    np.testing.assert_allclose(
        float(scores[0]),
        _greedy_score(dec, params, ids[:1], 2),
        rtol=1e-4,
        atol=1e-4,
    )
    # Beams are distinct sequences, best first.
    assert len({tuple(np.asarray(r)) for r in ids}) == 4
    assert (np.diff(np.asarray(scores)) <= 1e-6).all()


def test_beam_validation():
    dec = tiny_gpt(16)
    params = dec.init(jax.random.key(0))
    with pytest.raises(ValueError, match="one prompt"):
        beam_search(dec, params, jnp.zeros((2, 3), jnp.int32), 2)
    with pytest.raises(ValueError, match="beam_size"):
        beam_search(dec, params, jnp.zeros((1, 3), jnp.int32), 2, beam_size=0)
    with pytest.raises(ValueError, match="max_len"):
        beam_search(dec, params, jnp.zeros((1, 10), jnp.int32), 10)


def test_beam_on_rolling_cache_long_prompt():
    """Rolling-cache decoders beam-search past the window: the prompt
    chunks through prefill and beam 1 still equals greedy."""
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import mistral_config

    cfg = mistral_config(
        num_layers=2, dim=64, num_heads=4, num_kv_heads=2,
        ffn_dim=128, vocab_size=96, max_len=32, window=4,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.float32, rolling_cache=True)
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 7), 0, 96)
    want = dec.generate(params, prompt, 6)
    got, _ = beam_search(dec, params, prompt, 6, beam_size=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    ids, scores = beam_search(dec, params, prompt, 6, beam_size=3)
    assert ids.shape == (3, 13) and bool(jnp.isfinite(scores).all())
