"""Host transport (DCN seam) and multi-host mesh layout."""

import threading

import jax
import numpy as np
import pytest

from defer_tpu.parallel.multihost import dcn_aware_axes, initialize
from defer_tpu.runtime.transport import (
    ArrayReceiver,
    ArraySender,
    TransportError,
)


def _loopback_pair(**sender_kwargs):
    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=10.0)
    send = ArraySender("127.0.0.1", recv.port, **sender_kwargs)
    return send, recv


@pytest.mark.parametrize("compress", [True, False])
def test_stream_arrays_round_trip(compress):
    send, recv = _loopback_pair(compress=compress)
    arrays = [
        np.random.default_rng(i).standard_normal((4, 8)).astype(np.float32)
        for i in range(5)
    ]
    got = []

    def consume():
        got.extend(recv)

    t = threading.Thread(target=consume)
    t.start()
    for a in arrays:
        send.send(a)
    send.close()
    t.join(timeout=10)
    assert not t.is_alive()
    assert len(got) == len(arrays)
    for a, b in zip(arrays, got):
        np.testing.assert_array_equal(a, b)
    recv.close()


def test_pipeline_hop_over_transport():
    """A two-'host' pipeline: stage 0 in this thread, stage 1 behind a
    loopback transport — the reference's node chain, modernized."""
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.graph.partition import partition, stage_params

    b = GraphBuilder("two_host")
    x = b.input()
    h = b.add("dense", x, name="s0", features=8)
    h = b.add("relu", h, name="s0_relu")
    h = b.add("dense", h, name="s1", features=4)
    g = b.build(h)
    params = g.init(jax.random.key(0), (2, 8))
    st0, st1 = partition(g, ["s0_relu"])

    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=10.0)
    outs = []

    def remote_stage():
        p1 = stage_params(params, st1)
        for act in recv:
            outs.append(np.asarray(st1.apply(p1, act)))

    t = threading.Thread(target=remote_stage)
    t.start()
    send = ArraySender("127.0.0.1", recv.port)
    xin = np.random.default_rng(1).standard_normal((2, 8)).astype(np.float32)
    n = 4
    for _ in range(n):
        act = st0.apply(stage_params(params, st0), xin)
        send.send(np.asarray(act))
    send.close()
    t.join(timeout=10)
    recv.close()
    assert len(outs) == n
    want = np.asarray(g.apply(params, xin))
    for o in outs:
        np.testing.assert_allclose(o, want, rtol=1e-5)


def test_receiver_accept_timeout():
    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=0.2)
    with pytest.raises(TransportError, match="accept timeout"):
        list(recv)
    recv.close()


def test_sender_connect_failure():
    with pytest.raises(TransportError, match="could not connect"):
        ArraySender("127.0.0.1", 1, retries=2, connect_timeout_s=0.2)


def test_dcn_aware_axes_single_host_identity():
    axes = {"model": 4, "data": 2}
    assert dcn_aware_axes(axes) == axes  # 1 process: unchanged


def test_dcn_aware_axes_reorders_for_multihost(monkeypatch):
    import defer_tpu.parallel.multihost as mh

    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
    out = mh.dcn_aware_axes({"model": 4, "data": 2, "stage": 2})
    # data/stage move to the outside (host-spanning), model stays inner.
    assert list(out) == ["data", "stage", "model"]
    assert out == {"data": 2, "stage": 2, "model": 4}


def test_initialize_single_process_noop():
    topo = initialize()
    assert topo["process_count"] == 1
    assert topo["global_devices"] >= 1


def test_receiver_read_timeout_surfaces_stalled_peer():
    """A peer that connects and then goes silent must surface as a
    TransportError after read_timeout_s, not block forever."""
    recv = ArrayReceiver(
        0, host="127.0.0.1", accept_timeout_s=5.0, read_timeout_s=0.2
    )
    send = ArraySender("127.0.0.1", recv.port)
    # send nothing: the receiver accepts, then stalls on the first
    # header read until the timeout trips
    with pytest.raises(TransportError, match="timed out"):
        next(iter(recv))
    send.close()
    recv.close()


def test_sender_backoff_validation():
    with pytest.raises(ValueError, match="backoff"):
        ArraySender("127.0.0.1", 1, backoff_base_s=-0.1)
    with pytest.raises(ValueError, match="backoff"):
        ArraySender("127.0.0.1", 1, backoff_base_s=1.0, backoff_cap_s=0.5)


def test_wire_byte_accounting_sender_receiver_agree():
    """send() returns the frame's wire bytes and the receiver's
    rx_frame_bytes counts the same total — the per-stream accounting
    the disagg byte counters are built on."""
    send, recv = _loopback_pair()
    arrays = [
        np.arange(24, dtype=np.float32).reshape(4, 6),
        np.zeros((0, 3), np.int32),
    ]
    got = []

    def consume():
        got.extend(recv)

    t = threading.Thread(target=consume)
    t.start()
    sent = sum(send.send(a) for a in arrays)
    send.close()
    t.join(timeout=10)
    assert len(got) == len(arrays)
    assert sent == recv.rx_frame_bytes > 0
    recv.close()
