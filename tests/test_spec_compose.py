"""Real draft models + speculation composition (ISSUE 16).

Two contracts, one file. (1) `models/transplant.py::make_draft`
carves layer-truncated and width-pruned drafts out of a GPT target,
and `DraftLanes` validates draft-vs-target geometry with the fix
spelled out. (2) Every newly composed speculation path — spec x
decode_window (fused rounds), spec on submit_prefilled admissions
(disagg decode), spec under fleet routing, spec on a tp=2 mesh —
emits greedy token streams BIT-IDENTICAL to spec_k=0, for a
full-accept self-draft AND a divergent draft that forces the
rejection/rewrite path every round.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import GptDecoder, SamplingParams, tiny_gpt
from defer_tpu.models.transplant import (
    TransplantError,
    draft_width_geometry,
    make_draft,
)
from defer_tpu.runtime.decode_server import DraftLanes
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


@pytest.fixture(scope="module")
def gqa_model():
    """GQA target (4 query heads sharing 2 kv heads) so width pruning
    exercises the head-slicing path — tiny_gpt is MHA, where width
    can only prune FFN."""
    cfg = dataclasses.replace(
        tiny_gpt(64).cfg, num_kv_heads=2, pos_style="rope"
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.float32)
    return dec, dec.init(jax.random.key(2))


@pytest.fixture(scope="module")
def divergent_draft():
    dec = tiny_gpt(64)
    return dec, dec.init(jax.random.key(7))


def _requests(vocab):
    rng = np.random.default_rng(11)
    return [
        (jnp.asarray(rng.integers(1, vocab, size=(1, 8)), jnp.int32), 9),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 3)), jnp.int32), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 1)), jnp.int32), 7),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 5)), jnp.int32), 12),
    ]


def _sampling():
    return [
        None,
        SamplingParams(temperature=0.9, seed=13),
        None,
        SamplingParams(temperature=1.0, top_k=8, seed=5),
    ]


@pytest.fixture(scope="module")
def baseline(model):
    dec, params = model
    outs, stats = serve_paged(
        dec, params, _requests(dec.cfg.vocab_size), num_blocks=24,
        block_size=8, max_batch=2, sampling=_sampling(),
    )
    return outs, stats


def _assert_parity(want, got, tag):
    for j, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=f"{tag} req={j}"
        )


# -- draft construction ----------------------------------------------------


def test_make_draft_truncated_geometry(model):
    dec, params = model
    draft, dparams = make_draft(dec, params, layers=2)
    assert draft.cfg.num_layers == 2
    assert draft.cfg.dim == dec.cfg.dim
    assert draft.cfg.vocab_size == dec.cfg.vocab_size
    assert dparams["stack"]["wq"].shape[0] == 2
    # Sliced layers are the target's own first layers, not copies of
    # something else.
    np.testing.assert_array_equal(
        np.asarray(dparams["stack"]["wq"]),
        np.asarray(params["stack"]["wq"][:2]),
    )
    np.testing.assert_array_equal(
        np.asarray(dparams["token_embedding"]),
        np.asarray(params["token_embedding"]),
    )
    # The draft is a runnable decoder.
    logits, _ = draft.make_step(donate=False)(
        dparams, draft.init_cache(1), jnp.ones((1, 1), jnp.int32)
    )
    assert logits.shape == (1, 1, dec.cfg.vocab_size)


def test_make_draft_width_pruned_geometry(gqa_model):
    dec, params = gqa_model
    heads, dim, ffn = draft_width_geometry(dec.cfg, 0.5)
    assert heads == 2 and dim == 32 and ffn == 64
    draft, dparams = make_draft(dec, params, width=0.5)
    assert draft.cfg.num_heads == heads
    assert draft.cfg.dim == dim
    assert draft.cfg.ffn_dim == ffn
    # KV width is invariant: the draft attends with the target's
    # kv_heads (DraftLanes geometry contract).
    assert draft.cfg.kv_heads == dec.cfg.kv_heads
    assert draft.cfg.rope_theta == dec.cfg.rope_theta
    st = dparams["stack"]
    assert st["wq"].shape == (dec.cfg.num_layers, dim, dim)
    assert st["w1"].shape == (dec.cfg.num_layers, dim, ffn)
    logits, _ = draft.make_step(donate=False)(
        dparams, draft.init_cache(1), jnp.ones((1, 1), jnp.int32)
    )
    assert logits.shape == (1, 1, dec.cfg.vocab_size)


def test_make_draft_int8_and_errors(model):
    dec, params = model
    draft, dparams = make_draft(dec, params, layers=2, dtype="int8")
    assert dparams["stack"]["wq"]["q"].dtype == jnp.int8
    logits, _ = draft.make_step(donate=False)(
        dparams, draft.init_cache(1), jnp.ones((1, 1), jnp.int32)
    )
    assert logits.shape == (1, 1, dec.cfg.vocab_size)
    with pytest.raises(TransplantError, match="layers"):
        make_draft(dec, params, layers=0)
    with pytest.raises(TransplantError, match="layers"):
        make_draft(dec, params, layers=99)
    with pytest.raises(TransplantError, match="width"):
        make_draft(dec, params, width=1.5)
    with pytest.raises(TransplantError, match="quantized"):
        make_draft(dec, dparams, layers=1)


def test_draft_lanes_geometry_validation(model):
    dec, params = model
    bad_vocab = GptDecoder(
        dataclasses.replace(dec.cfg, vocab_size=64), jnp.float32
    )
    with pytest.raises(ValueError, match="vocab_size.*make_draft"):
        DraftLanes(
            bad_vocab, bad_vocab.init(jax.random.key(1)), 2, target=dec
        )
    bad_kv = GptDecoder(
        dataclasses.replace(dec.cfg, num_kv_heads=2), jnp.float32
    )
    with pytest.raises(ValueError, match="kv_heads.*width"):
        DraftLanes(bad_kv, bad_kv.init(jax.random.key(1)), 2, target=dec)
    bad_pos = GptDecoder(
        dataclasses.replace(dec.cfg, pos_style="rope"), jnp.float32
    )
    with pytest.raises(ValueError, match="pos_style"):
        DraftLanes(
            bad_pos, bad_pos.init(jax.random.key(1)), 2, target=dec
        )
    rope = dataclasses.replace(dec.cfg, pos_style="rope")
    rope_target = GptDecoder(rope, jnp.float32)
    bad_theta = GptDecoder(
        dataclasses.replace(rope, rope_theta=500000.0), jnp.float32
    )
    with pytest.raises(ValueError, match="rope_theta"):
        DraftLanes(
            bad_theta, bad_theta.init(jax.random.key(1)), 2,
            target=rope_target,
        )
    # A transplant-carved draft passes by construction.
    draft, dparams = make_draft(dec, params, layers=2)
    DraftLanes(draft, dparams, 2, target=dec)


# -- composed-path parity --------------------------------------------------


@pytest.mark.parametrize("which_draft", ["self", "divergent", "trunc"])
def test_spec_window_parity(model, divergent_draft, baseline, which_draft):
    """Fused spec x decode_window: W whole draft+verify rounds per
    host dispatch, token-identical to spec_k=0 for a full-accept
    self-draft, an always-reject divergent draft, and a real
    transplant-carved draft in between."""
    dec, params = model
    want, _ = baseline
    draft, dparams = {
        "self": lambda: model,
        "divergent": lambda: divergent_draft,
        "trunc": lambda: make_draft(dec, params, layers=2),
    }[which_draft]()
    outs, stats = serve_paged(
        dec, params, _requests(dec.cfg.vocab_size), num_blocks=24,
        block_size=8, max_batch=2, sampling=_sampling(),
        spec_draft=draft, spec_params=dparams, spec_k=2,
        decode_window=4,
    )
    _assert_parity(want, outs, f"spec-window {which_draft}")
    assert stats["spec_rounds"] > 0
    if which_draft == "divergent":
        assert stats["spec_acceptance"] < 0.5


def test_spec_window_dispatch_amortization(model):
    """The acceptance criterion: W=4, k>=2 needs dispatches-per-token
    <= 1/W of the k=0, W=1 baseline (the window fuses W two-forward
    rounds into ONE dispatch, and each round commits up to k+1 tokens
    per slot)."""
    dec, params = model
    req = [(jnp.asarray([[3, 9, 27]], jnp.int32), 17)]

    def dispatches_per_token(**kwargs):
        _, stats = serve_paged(
            dec, params, req, num_blocks=16, block_size=8, max_batch=1,
            **kwargs,
        )
        return stats["host_dispatches"] / 17

    base = dispatches_per_token()
    fused = dispatches_per_token(
        spec_draft=dec, spec_params=params, spec_k=2, decode_window=4
    )
    assert fused <= base / 4
    # k=0, W=1 pays ~one dispatch per token (the first token comes
    # free at admission: 16 dispatches for 17 tokens).
    assert base == pytest.approx(16 / 17)


@pytest.mark.parametrize("which_draft", ["self", "divergent"])
def test_spec_disagg_parity(model, divergent_draft, baseline, which_draft):
    """Spec over submit_prefilled admissions: target KV arrives over
    the wire, the draft lane re-prefills locally — greedy outputs
    stay identical to the non-speculative split."""
    from defer_tpu.disagg.api import serve_disagg

    dec, params = model
    want, _ = baseline
    draft, dparams = (
        model if which_draft == "self" else divergent_draft
    )
    outs, stats = serve_disagg(
        dec, params, _requests(dec.cfg.vocab_size), num_blocks=24,
        block_size=8, max_batch=2, sampling=_sampling(),
        spec_draft=draft, spec_params=dparams, spec_k=3,
    )
    _assert_parity(want, outs, f"spec-disagg {which_draft}")
    assert stats["disagg"] and stats["spec_rounds"] > 0
    if which_draft == "divergent":
        assert stats["spec_acceptance"] < 0.5


@pytest.mark.parametrize("which_draft", ["self", "divergent"])
def test_spec_fleet_parity(model, divergent_draft, baseline, which_draft):
    """Spec under fleet routing: every replica speculates with its
    own DraftLanes; outputs match single-server spec_k=0."""
    from defer_tpu.fleet.api import serve_fleet

    dec, params = model
    want, _ = baseline
    draft, dparams = (
        model if which_draft == "self" else divergent_draft
    )
    outs, stats = serve_fleet(
        dec, params, _requests(dec.cfg.vocab_size), n_replicas=2,
        num_blocks=24, block_size=8, max_batch=2, sampling=_sampling(),
        spec_draft=draft, spec_params=dparams, spec_k=3,
    )
    _assert_parity(want, outs, f"spec-fleet {which_draft}")
    per = stats["replicas"]
    assert sum(r["spec_rounds"] for r in per) > 0
    assert all(r["spec_k"] == 3 for r in per)


@pytest.mark.parametrize("decode_window", [1, 4])
def test_spec_tp_parity(model, baseline, decode_window):
    """Spec on a {"model": 2} mesh (draft replicated, verify forward
    sharded), with and without the fused window — conftest provides 8
    virtual CPU devices."""
    from defer_tpu.parallel.mesh import make_mesh

    dec, params = model
    want, _ = baseline
    mesh = make_mesh({"model": 2}, jax.devices()[:2])
    outs, stats = serve_paged(
        dec, params, _requests(dec.cfg.vocab_size), num_blocks=24,
        block_size=8, max_batch=2, sampling=_sampling(), mesh=mesh,
        spec_draft=dec, spec_params=params, spec_k=2,
        decode_window=decode_window,
    )
    _assert_parity(want, outs, f"spec-tp W={decode_window}")
    assert stats["mesh_shape"] == "model=2"
    assert stats["spec_rounds"] > 0


# -- satellite: lane release + obs -----------------------------------------


def test_draft_lane_released_on_mid_round_finish(model):
    """A slot finishing inside a spec round (eos mid-window) must
    leave its draft lane FULLY cleared — pos zeroed and cache rows
    zeroed — so the next tenant of the slot never attends over a dead
    request's K/V."""
    dec, params = model
    req = (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32), 9)
    base, _ = serve_paged(
        dec, params, [req], num_blocks=16, block_size=8, max_batch=1
    )
    toks = np.asarray(base[0])[0]
    eos = int(toks[req[0].shape[1] + 3])
    srv = PagedDecodeServer(
        dec, params, num_blocks=16, block_size=8, max_batch=1,
        eos_id=eos, spec_draft=dec, spec_params=params, spec_k=4,
    )
    srv.submit(req[0], req[1])
    srv.run()
    assert srv._draft.pos[0] == 0
    assert not np.asarray(srv._draft.ck[:, 0]).any()
    assert not np.asarray(srv._draft.cv[:, 0]).any()
    # release_all (the fleet replica-death path) clears every lane.
    srv._draft.pos[0] = 7
    srv._draft.ck = srv._draft.ck.at[:, 0].set(1.0)
    srv._draft.release_all()
    assert not srv._draft.pos.any()
    assert not np.asarray(srv._draft.ck).any()


def test_spec_obs_counters_and_histogram(model):
    """Counter pins for the new obs surface: the draft-side forward
    counter matches the stats field, and defer_spec_acceptance is a
    HISTOGRAM of per-round accepted lengths (self-draft: every greedy
    round observes exactly k, so sum == count * k)."""
    dec, params = model
    req = [(jnp.asarray([[3, 9, 27]], jnp.int32), 9)]
    reg = obs.get_registry()
    before = reg.value("defer_spec_acceptance", server="paged") or {
        "count": 0,
        "sum": 0.0,
    }
    with obs.counter_deltas() as d:
        _, stats = serve_paged(
            dec, params, req, num_blocks=16, block_size=8, max_batch=2,
            spec_draft=dec, spec_params=params, spec_k=4,
        )
    assert stats["spec_draft_tokens"] > 0
    assert (
        d.get('defer_spec_draft_tokens_total{server="paged"}', 0)
        == stats["spec_draft_tokens"]
    )
    after = reg.value("defer_spec_acceptance", server="paged")
    n = after["count"] - before["count"]
    s = after["sum"] - before["sum"]
    # One observation per greedy-slot round (one slot here).
    assert n == stats["spec_rounds"]
    # Self-draft: every observed round accepted the full k proposals.
    assert s == pytest.approx(n * 4)
