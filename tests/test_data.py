"""Host input pipeline: preprocess, batching, device prefetch."""

import numpy as np
import pytest

from defer_tpu.runtime.data import (
    batched,
    imagenet_preprocess,
    prefetch_to_device,
)


def test_preprocess_scale_mode_range():
    img = np.random.default_rng(0).integers(0, 256, (224, 224, 3), np.uint8)
    out = imagenet_preprocess(img)
    assert out.shape == (1, 224, 224, 3)
    assert out.dtype == np.float32
    assert -1.0 <= out.min() and out.max() <= 1.0


def test_preprocess_resizes_and_crops():
    imgs = np.zeros((2, 300, 400, 3), np.uint8)
    out = imagenet_preprocess(imgs, size=224)
    assert out.shape == (2, 224, 224, 3)


def test_numpy_resize_matches_jax_bilinear():
    import jax

    from defer_tpu.runtime.data import _bilinear_resize_np

    x = np.random.default_rng(3).random((2, 37, 53, 3)).astype(np.float32)
    got = _bilinear_resize_np(x, 24, 24)
    # antialias=False: Keras preprocessing uses plain (non-antialiased)
    # bilinear sampling, which is what the numpy path implements.
    want = np.asarray(
        jax.image.resize(x, (2, 24, 24, 3), "bilinear", antialias=False)
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_preprocess_caffe_mode_bgr():
    img = np.zeros((1, 224, 224, 3), np.float32)
    img[..., 0] = 255.0  # R
    out = imagenet_preprocess(img, mode="caffe")
    # BGR order: R lands in the last channel, minus its mean.
    np.testing.assert_allclose(out[0, 0, 0, 2], 255.0 - 123.68, rtol=1e-6)
    np.testing.assert_allclose(out[0, 0, 0, 0], -103.939, rtol=1e-6)


def test_batched_drops_tail_by_default():
    examples = [np.full((2,), i, np.float32) for i in range(7)]
    batches = list(batched(examples, 3))
    assert len(batches) == 2
    assert batches[0].shape == (3, 2)
    batches = list(batched(examples, 3, drop_remainder=False))
    assert len(batches) == 3
    assert batches[-1].shape == (1, 2)


def test_prefetch_yields_device_arrays_in_order():
    import jax

    items = [np.full((4,), i, np.float32) for i in range(10)]
    out = list(prefetch_to_device(iter(items), depth=3))
    assert len(out) == 10
    for i, arr in enumerate(out):
        assert isinstance(arr, jax.Array)
        assert float(arr[0]) == i


def test_prefetch_feeder_terminates_on_abandoned_consumer():
    """Breaking out of a prefetch loop must unblock the feeder thread
    (no leaked iterator / device buffers)."""
    import threading
    import time

    released = threading.Event()

    def gen():
        try:
            for i in range(1000):
                yield np.full((2,), i, np.float32)
        finally:
            released.set()

    it = prefetch_to_device(gen(), depth=2)
    next(it)
    it.close()  # what GC does to a partially-consumed generator
    for _ in range(50):
        if released.is_set():
            break
        time.sleep(0.1)
    assert released.is_set(), "feeder thread still pinned after abandon"


def test_prefetch_propagates_source_errors():
    def gen():
        yield np.zeros(3, np.float32)
        raise ValueError("bad input stream")

    it = prefetch_to_device(gen())
    next(it)
    with pytest.raises(ValueError, match="bad input stream"):
        list(it)


def test_decode_preprocess_infer_end_to_end(tmp_path, devices):
    """Real image files -> decode -> preprocess -> batch -> prefetch ->
    2-stage pipeline (the reference's full input path, reference
    src/test.py:13-16, with actual decoding)."""
    import jax
    import jax.numpy as jnp
    from PIL import Image

    from defer_tpu.config import DeferConfig
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.graph.partition import partition
    from defer_tpu.runtime.data import load_image_dir

    rng = np.random.RandomState(7)
    for i, shape in enumerate([(40, 56, 3), (64, 32, 3), (48, 48, 3)]):
        Image.fromarray(
            rng.randint(0, 256, shape).astype(np.uint8)
        ).save(tmp_path / f"im{i}.png")

    decoded = list(load_image_dir(str(tmp_path)))
    assert len(decoded) == 3
    assert all(d.dtype == np.uint8 and d.shape[-1] == 3 for d in decoded)

    b = GraphBuilder("tiny")
    x = b.input()
    x = b.add("conv", x, name="c1", features=4, kernel_size=3,
              strides=2, padding="SAME")
    x = b.add("relu", x, name="r1")
    x = b.add("conv", x, name="c2", features=8, kernel_size=3,
              padding="SAME")
    x = b.add("global_avg_pool", x, name="gap")
    g = b.build(b.add("dense", x, name="fc", features=5))
    params = g.init(jax.random.key(0), (2, 32, 32, 3))

    from defer_tpu.parallel.pipeline import Pipeline

    pipe = Pipeline(
        partition(g, ["r1"]), params, jax.devices()[:2],
        DeferConfig(compute_dtype=jnp.float32),
    )
    stream = prefetch_to_device(
        batched(
            (imagenet_preprocess(im, size=32)[0] for im in decoded),
            batch_size=2,
        ),
        jax.devices()[0],
    )
    outs = [np.asarray(pipe(xb)) for xb in stream]
    assert len(outs) == 1  # 3 images -> one full batch of 2, tail dropped
    assert outs[0].shape == (2, 5)
    # Exact parity with the unpipelined graph on the same preprocessed
    # batch.
    xb = np.concatenate(
        [imagenet_preprocess(im, size=32) for im in decoded[:2]]
    )
    np.testing.assert_allclose(
        outs[0], np.asarray(g.apply(params, xb)), rtol=1e-5, atol=1e-6
    )


def test_native_preprocess_matches_numpy():
    """The fused C++ preprocessor must match the numpy path on every
    mode, dtype, and geometry (resize-down, resize-up, identity)."""
    import ml_dtypes

    from defer_tpu.runtime.native_image import (
        native_available,
        native_preprocess,
    )

    if not native_available():
        pytest.skip("no native toolchain; numpy fallback covers this host")
    rng = np.random.RandomState(3)
    # (1, 89, 64, 3) pins the half-to-even rounding case: 89*0.5 = 44.5
    # must round to 44 (numpy round()), not 45 (llround).
    for shape in [(2, 50, 70, 3), (1, 96, 40, 3), (1, 32, 32, 3),
                  (1, 89, 64, 3)]:
        imgs = rng.randint(0, 256, shape).astype(np.uint8)
        for mode in ("scale", "unit", "caffe"):
            got = native_preprocess(imgs, size=32, mode=mode)
            assert got is not None and got.dtype == np.float32
            # Reference numpy path (bypass the native fast path by
            # feeding float input).
            want = imagenet_preprocess(
                imgs.astype(np.float32), size=32, mode=mode
            )
            np.testing.assert_allclose(got, want, atol=2e-3)
            # bf16 output: same values rounded to bfloat16.
            got16 = native_preprocess(
                imgs, size=32, mode=mode, out_dtype=ml_dtypes.bfloat16
            )
            assert got16.dtype == np.dtype(ml_dtypes.bfloat16)
            np.testing.assert_allclose(
                got16.astype(np.float32),
                want.astype(ml_dtypes.bfloat16).astype(np.float32),
                atol=2.0 if mode == "caffe" else 2e-2,
            )


def test_uint8_preprocess_uses_native_and_matches():
    """imagenet_preprocess(uint8) routes through the native path and
    agrees with the float path."""
    from defer_tpu.runtime.native_image import native_available

    if not native_available():
        pytest.skip("no native toolchain; numpy fallback covers this host")
    rng = np.random.RandomState(4)
    imgs = rng.randint(0, 256, (2, 41, 63, 3)).astype(np.uint8)
    got = imagenet_preprocess(imgs, size=24, mode="caffe")
    want = imagenet_preprocess(imgs.astype(np.float32), size=24, mode="caffe")
    np.testing.assert_allclose(got, want, atol=2e-3)
