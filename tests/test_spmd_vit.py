"""SpmdVit: pre-LN blocks + patch embed on the circular SPMD pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.vit import SpmdVit
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.transformer_stack import (
    TransformerConfig,
    init_stack,
    layers_apply,
)

pytestmark = pytest.mark.slow


def _cfg(**kw):
    base = dict(
        num_layers=4,
        dim=32,
        num_heads=4,
        ffn_dim=64,
        vocab_size=1,
        max_len=64,
        norm_style="pre",
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_pre_ln_block_matches_manual_reference():
    """layers_apply with norm_style='pre' == a hand-written pre-LN
    block (independent implementation, not shard_map)."""
    cfg = _cfg(num_layers=1)
    p = init_stack(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 6, cfg.dim))

    def ln(v, scale, bias):
        m = v.mean(-1, keepdims=True)
        s = ((v - m) ** 2).mean(-1, keepdims=True)
        return (v - m) / np.sqrt(s + cfg.layer_norm_eps) * scale + bias

    q1 = {k: np.asarray(v[0], np.float64) for k, v in p.items()}
    xv = np.asarray(x, np.float64)
    h = ln(xv, q1["ln1_scale"], q1["ln1_bias"])
    q = h @ q1["wq"] + q1["bq"]
    k = h @ q1["wk"] + q1["bk"]
    v = h @ q1["wv"] + q1["bv"]

    def heads(t):
        b, s, d = t.shape
        return t.reshape(b, s, 4, d // 4).transpose(0, 2, 1, 3)

    qh, kh, vh = heads(q), heads(k), heads(v)
    logits = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(qh.shape[-1])
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    a = (w @ vh).transpose(0, 2, 1, 3).reshape(xv.shape)
    xv = xv + (a @ q1["wo"] + q1["bo"])
    h2 = ln(xv, q1["ln2_scale"], q1["ln2_bias"])
    ff = h2 @ q1["w1"] + q1["b1"]
    # jax.nn.gelu defaults to the tanh approximation — mirror it.
    ff = (
        0.5
        * ff
        * (1 + np.tanh(np.sqrt(2 / np.pi) * (ff + 0.044715 * ff**3)))
    )
    want = xv + (ff @ q1["w2"] + q1["b2"])

    got = layers_apply(p, x, cfg)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_pre_and_post_ln_differ():
    cfg_pre, cfg_post = _cfg(), _cfg(norm_style="post")
    p = init_stack(jax.random.key(0), cfg_pre)
    x = jax.random.normal(jax.random.key(1), (2, 6, cfg_pre.dim))
    out_pre = layers_apply(p, x, cfg_pre)
    out_post = layers_apply(p, x, cfg_post)
    assert not np.allclose(np.asarray(out_pre), np.asarray(out_post))


def test_spmd_vit_pipeline_matches_reference(devices):
    """dp x pp x tp SpmdVit: pipelined step == unpipelined reference."""
    mesh = make_mesh({"data": 2, "stage": 2, "model": 2}, devices[:8])
    sv = SpmdVit(
        mesh,
        _cfg(),
        image_size=16,
        patch_size=4,
        num_classes=5,
        compute_dtype=jnp.float32,
    )
    params = sv.init(jax.random.key(0))
    num_mb, batch = 4, 4
    images = jax.random.normal(
        jax.random.key(1), (num_mb, batch, 16, 16, 3)
    )
    step = sv.make_step()
    got = step(params, images)
    want = sv.reference_apply(params, images)
    assert got.shape == (num_mb, batch, 5)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_spmd_vit_inits_with_lora(devices):
    """A LoRA-enabled config must produce matching param/spec trees
    (ViT fine-tuning is a primary adapter use-case)."""
    import dataclasses

    mesh = make_mesh({"stage": 2, "model": 2}, devices[:4])
    cfg = dataclasses.replace(_cfg(), lora_rank=4)
    sv = SpmdVit(
        mesh, cfg, image_size=16, patch_size=4, num_classes=5,
        compute_dtype=jnp.float32,
    )
    params = sv.init(jax.random.key(0))
    assert "wq:a" in params["stack"] and "wv:b" in params["stack"]
    images = jax.random.normal(jax.random.key(1), (2, 2, 16, 16, 3))
    out = sv.make_step()(params, images)
    assert out.shape == (2, 2, 5)


def test_spmd_vit_fsdp_matches_replicated(devices):
    """SpmdVit(fsdp=True): weights rest data-sharded, outputs equal
    the replicated run."""
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    kw = dict(image_size=16, patch_size=4, num_classes=5,
              compute_dtype=jnp.float32)
    sv0 = SpmdVit(mesh, _cfg(), **kw)
    sv1 = SpmdVit(mesh, _cfg(), fsdp=True, **kw)
    p0 = sv0.init(jax.random.key(0))
    p1 = sv1.init(jax.random.key(0))
    assert "data" in tuple(p1["stack"]["w1"].sharding.spec)
    images = jax.random.normal(jax.random.key(1), (2, 2, 16, 16, 3))
    o0 = sv0.make_step()(p0, images)
    o1 = sv1.make_step()(p1, images)
    np.testing.assert_allclose(
        np.asarray(o1), np.asarray(o0), rtol=1e-5, atol=1e-5
    )


def test_spmd_vit_validates_config(devices):
    mesh = make_mesh({"stage": 2}, devices[:2])
    import pytest

    with pytest.raises(ValueError, match="pre"):
        SpmdVit(mesh, _cfg(norm_style="post"), image_size=16, patch_size=4)
    with pytest.raises(ValueError, match="divisible"):
        SpmdVit(mesh, _cfg(num_layers=3), image_size=16, patch_size=4)
    with pytest.raises(ValueError, match="not divisible"):
        SpmdVit(mesh, _cfg(), image_size=17, patch_size=4)
