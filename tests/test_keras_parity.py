"""Numerical parity against REAL tf.keras models.

The reference's contract is running actual Keras artifacts: nodes
rebuild shipped models with `model_from_json` + `set_weights`
(reference src/node.py:38-45) and the drivers load
`ResNet50(weights='imagenet')` (reference src/local_infer.py:8). Here
real `tf.keras` models (random weights — no network) are exported with
`to_json()` + `save_weights()`, ingested through `model_from_keras` /
`transplant`, and the JAX forward must reproduce TF's forward.

Two paths are covered per model:
  * JSON path: real Keras JSON + .weights.h5 -> IR graph (identity
    names) -> outputs match TF.
  * Native-zoo path: the hand-built zoo graph consumes the same real
    checkpoint via its `keras_name_map` -> outputs match TF.
"""

import os

os.environ.setdefault("TF_ENABLE_ONEDNN_OPTS", "0")
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "")

import numpy as np
import pytest

pytestmark = pytest.mark.slow

tf = pytest.importorskip("tensorflow")

import jax  # noqa: E402

from defer_tpu.graph.keras_import import model_from_keras  # noqa: E402
from defer_tpu.models import get_model  # noqa: E402
from defer_tpu.models.transplant import (  # noqa: E402
    KerasWeights,
    load_keras_h5,
    transplant,
)

_BUILDERS = {
    "resnet50": lambda: tf.keras.applications.ResNet50(weights=None),
    "mobilenetv2": lambda: tf.keras.applications.MobileNetV2(weights=None),
    "inceptionv3": lambda: tf.keras.applications.InceptionV3(weights=None),
    "vgg16": lambda: tf.keras.applications.VGG16(weights=None),
    "efficientnet_b0": lambda: tf.keras.applications.EfficientNetB0(
        weights=None
    ),
    "inception_resnet_v2": lambda: tf.keras.applications.InceptionResNetV2(
        weights=None
    ),
    "nasnet_mobile": lambda: tf.keras.applications.NASNetMobile(weights=None),
    "xception": lambda: tf.keras.applications.Xception(weights=None),
}


@pytest.fixture(scope="module")
def keras_artifacts(tmp_path_factory):
    """name -> (json_str, weights_path, tf_output, x) built once."""
    cache = {}

    def build(name):
        if name not in cache:
            km = _BUILDERS[name]()
            path = str(
                tmp_path_factory.mktemp("kw") / f"{name}.weights.h5"
            )
            km.save_weights(path)
            h, w = km.input_shape[1:3]
            x = np.random.RandomState(0).rand(1, h, w, 3).astype("float32")
            y_tf = np.asarray(km(x, training=False))
            cache[name] = (km.to_json(), path, y_tf, x)
        return cache[name]

    return build


def _assert_close(y_jax, y_tf, name):
    y_jax = np.asarray(y_jax)
    assert y_jax.shape == y_tf.shape
    # Outputs are softmax probabilities (~1e-3 each for random weights);
    # compare on the same scale.
    np.testing.assert_allclose(
        y_jax, y_tf, rtol=2e-3, atol=2e-6,
        err_msg=f"{name}: JAX forward diverged from tf.keras",
    )


@pytest.mark.parametrize(
    "name",
    [
        "resnet50",
        "mobilenetv2",
        "inceptionv3",
        "vgg16",
        "efficientnet_b0",
        "inception_resnet_v2",
        "nasnet_mobile",
        "xception",
    ],
)
def test_json_plus_h5_reproduces_tf_forward(name, keras_artifacts):
    json_str, weights_path, y_tf, x = keras_artifacts(name)
    model, params = model_from_keras(json_str, weights_h5=weights_path)
    assert params is not None
    y = model.graph.apply(params, x)
    _assert_close(y, y_tf, name)


@pytest.mark.parametrize(
    "name", ["resnet50", "mobilenetv2", "vgg16", "efficientnet_b0"]
)
def test_native_zoo_consumes_real_checkpoint(name, keras_artifacts):
    json_str, weights_path, y_tf, x = keras_artifacts(name)
    model = get_model(name)
    assert model.keras_name_map is not None
    base = model.init(jax.random.key(0))
    params = transplant(
        model.graph,
        base,
        KerasWeights(
            load_keras_h5(weights_path, json_str),
            name_map=model.keras_name_map,
        ),
        strict=True,
    )
    if name == "efficientnet_b0":
        # The native graph takes already-preprocessed input; the real
        # Keras model embeds Rescaling(1/255) + Normalization (identity
        # for an un-adapted model) at its head.
        x = x / 255.0
    y = model.graph.apply(params, x)
    _assert_close(y, y_tf, name)


def test_native_xception_matches_tf(keras_artifacts):
    """The hand-built Xception graph reproduces a real tf.keras
    Xception forward from its checkpoint. Keras auto-names the four
    residual-shortcut conv/BN pairs with global counters (`conv2d_7`
    if other models were built first), so the map resolves them from
    THIS model's JSON layer order instead of trusting fresh-session
    numbering."""
    import json as _json

    from defer_tpu.models.xception import _RES_ORDER

    json_str, weights_path, y_tf, x = keras_artifacts("xception")
    layers = _json.loads(json_str)["config"]["layers"]
    auto_convs = [
        l["config"]["name"]
        for l in layers
        if l["class_name"] == "Conv2D"
        and l["config"]["name"].startswith("conv2d")
    ]
    auto_bns = [
        l["config"]["name"]
        for l in layers
        if l["class_name"] == "BatchNormalization"
        and l["config"]["name"].startswith("batch_normalization")
    ]
    assert len(auto_convs) == len(auto_bns) == len(_RES_ORDER)
    remap = {f"{blk}_res_conv": cn for blk, cn in zip(_RES_ORDER, auto_convs)}
    remap |= {f"{blk}_res_bn": bn for blk, bn in zip(_RES_ORDER, auto_bns)}

    model = get_model("xception")
    def name_map(node, _inner=model.keras_name_map):
        return remap.get(node, _inner(node))

    base = model.init(jax.random.key(0))
    params = transplant(
        model.graph,
        base,
        KerasWeights(
            load_keras_h5(weights_path, json_str), name_map=name_map
        ),
        strict=True,
    )
    y = model.graph.apply(params, x)
    _assert_close(y, y_tf, "xception")


def test_imported_nasnet_pipelines_via_bundle_discovery(keras_artifacts):
    """A real NASNetMobile (no single-tensor cut inside the cell run)
    imports with auto-discovered bundle boundaries and a 4-stage
    bundle pipeline reproduces the full forward — the reference's wire
    protocol (one activation per hop) cannot express this at all."""
    from defer_tpu.graph.partition import partition, stage_params

    json_str, weights_path, y_tf, x = keras_artifacts("nasnet_mobile")
    model, params = model_from_keras(json_str, weights_h5=weights_path)
    assert any(isinstance(c, tuple) for c in model.cut_candidates)
    cuts = model.default_cuts(4)
    assert len(cuts) == 3
    h = x
    for s in partition(model.graph, cuts):
        h = s.apply(stage_params(params, s), h)
    _assert_close(h, y_tf, "nasnet_mobile.pipeline4")
