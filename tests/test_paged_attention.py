"""Block-native paged attention: the `blockwise` and `pallas` decode
paths must emit the same tokens as the `gathered` reference path.

Parity contract per path (runtime/paged.py module docstring): the
gathered path IS the flat decoder's block math, so it stays bit-exact
vs solo generate. The block-native paths share the exact projection
code (`_attn_qkv` / `_attn_out`) and differ only in softmax reduction
order, so logits may drift by float ulps; at these test scales no
argmax/sampling tie sits close enough for that to flip a token, and
the tests assert token-for-token equality — a mismatch means a real
indexing/masking bug, not tolerable drift.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.models.llama import tiny_llama
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


def _mixed_requests(vocab, rng_seed=5):
    """Five requests with a shared 16-token prefix on the first two
    (so prefix_cache=True actually shares blocks) and lengths that
    straddle block boundaries for both tested block sizes."""
    rng = np.random.default_rng(rng_seed)
    base = jnp.asarray(
        rng.integers(1, vocab, size=(1, 18)), jnp.int32
    )
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 5)), jnp.int32)
    return [
        (base, 6),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 3)), jnp.int32), 7),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 9)), jnp.int32), 4),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 2)), jnp.int32), 3),
    ]


# Greedy and sampled slots share ticks; the categorical draws consume
# the same logits, so a token mismatch here also catches drift that
# argmax alone would mask.
_MIXED_SAMPLING = [
    None,
    SamplingParams(temperature=0.9, seed=3),
    SamplingParams(temperature=1.2, top_k=5, seed=11),
    None,
    SamplingParams(temperature=1.0, top_p=0.9, seed=2),
]


def _serve(dec, params, reqs, *, attention, block_size, prefix_cache):
    outs, stats = serve_paged(
        dec, params, reqs,
        num_blocks=18, block_size=block_size, max_batch=2,
        prefix_cache=prefix_cache, sampling=_MIXED_SAMPLING,
        attention=attention,
    )
    return [np.asarray(o) for o in outs], stats


@pytest.mark.parametrize("block_size", [8, 16])
@pytest.mark.parametrize("prefix_cache", [False, True])
def test_blockwise_parity_matrix(block_size, prefix_cache):
    """blockwise == gathered token-for-token across block sizes x
    prefix-cache on/off, with mixed greedy+sampled slots and forced
    mid-stream finish/re-admit (5 requests through 2 slots). GQA
    model: the grouped-head reshape is the easiest thing to get
    subtly wrong."""
    dec = tiny_llama(64)
    params = dec.init(jax.random.key(0))
    reqs = _mixed_requests(dec.cfg.vocab_size)
    want, _ = _serve(
        dec, params, reqs, attention="gathered",
        block_size=block_size, prefix_cache=prefix_cache,
    )
    got, stats = _serve(
        dec, params, reqs, attention="blockwise",
        block_size=block_size, prefix_cache=prefix_cache,
    )
    assert stats["attention"] == "blockwise"
    for i, (a, b) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(
            a, b,
            err_msg=f"request {i} bs={block_size} cache={prefix_cache}",
        )


def test_blockwise_matches_solo_generate_gpt():
    """Absolute (not just relative) correctness on the learned-
    positions family: blockwise greedy outputs equal each request's
    solo dec.generate."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _mixed_requests(dec.cfg.vocab_size)
    outs, _ = serve_paged(
        dec, params, reqs, num_blocks=18, block_size=8, max_batch=2,
        attention="blockwise",
    )
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_kv_rows_scale_with_depth_not_pool():
    """The acceptance criterion for the whole PR, on the obs
    counters: the gathered path reads B * max_blocks * block_size
    rows per tick regardless of occupancy; blockwise reads only live
    depth — strictly fewer rows on the same workload, and the SAME
    row count when the pool grows (reads scale with request depth,
    not pool size)."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _mixed_requests(dec.cfg.vocab_size)

    def rows(attention, num_blocks):
        with obs.counter_deltas() as d:
            _, stats = serve_paged(
                dec, params, reqs, num_blocks=num_blocks,
                block_size=8, max_batch=2, attention=attention,
            )
        read = d.get('defer_kv_rows_read_total{server="paged"}', 0)
        base = d.get(
            'defer_kv_rows_gathered_baseline_total{server="paged"}', 0
        )
        return read, base, stats["ticks"]

    g_read, g_base, g_ticks = rows("gathered", 18)
    assert g_read == g_base > 0  # gathered reads the full view
    b_read, b_base, b_ticks = rows("blockwise", 18)
    assert b_ticks == g_ticks  # same schedule, comparable baselines
    assert b_base == g_base
    assert 0 < b_read < b_base  # depth-scaled reads beat the baseline
    # Growing the pool must not change what blockwise reads: both
    # pools admit the whole mix immediately, so the schedule — and
    # therefore live depth per tick — is identical.
    b_read2, _, b_ticks2 = rows("blockwise", 44)
    assert b_ticks2 == b_ticks
    assert b_read2 == b_read


def test_unknown_attention_mode_raises():
    dec = tiny_gpt(32)
    params = dec.init(jax.random.key(0))
    with pytest.raises(ValueError, match="attention"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=8, max_batch=2,
            attention="flash-gordon",
        )


def test_sampler_release_resets_policy_rows():
    """SlotSampler.release clears row_sort and the temperature row at
    finish, so one departed top-k request stops taxing later ticks
    with the sorting draw path."""
    from defer_tpu.runtime.decode_server import SlotSampler

    s = SlotSampler(3)
    logits_row = jnp.linspace(0.0, 1.0, 16)[None, :]
    s.admit_first(
        1,
        SamplingParams(temperature=0.8, top_k=4, seed=7),
        logits_row,
        jnp.int32,
    )
    assert s.row_sort[1] and s.row_temp[1] == 0.8
    assert float(s.temp[1]) == pytest.approx(0.8)
    s.release(1)
    assert not any(s.row_sort)
    assert s.row_temp[1] == 0.0
    assert float(s.temp[1]) == 0.0


def test_paged_server_releases_policy_at_finish():
    """End-to-end: after a paged run with top-k slots, every policy
    row is back to the greedy fast path."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    srv = PagedDecodeServer(
        dec, params, num_blocks=12, block_size=8, max_batch=2,
    )
    reqs = _mixed_requests(dec.cfg.vocab_size)[:3]
    for (p, s), sp in zip(reqs, _MIXED_SAMPLING):
        srv.submit(p, s, sampling=sp)
    srv.run()
    assert not any(srv._sampler.row_sort)
    assert all(t == 0.0 for t in srv._sampler.row_temp)


def test_paged_flash_decode_kernel_matches_reference():
    """Kernel-level (interpret mode): paged_flash_decode over a block
    table with trash entries equals a dense gather + masked softmax
    reference, per slot and per grouped head."""
    from defer_tpu.ops.pallas_attention import paged_flash_decode

    b, hq, hkv, d, bs, mb, nb = 3, 4, 2, 16, 8, 3, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    pk = jnp.asarray(
        rng.standard_normal((nb, hkv, bs, d)), jnp.float32
    )
    pv = jnp.asarray(
        rng.standard_normal((nb, hkv, bs, d)), jnp.float32
    )
    # Slot 0: full table. Slot 1: one live block, rest trash block 0.
    # Slot 2: two live blocks. pos is the last valid key, inclusive.
    tables = jnp.asarray(
        [[1, 2, 3], [4, 0, 0], [5, 6, 0]], jnp.int32
    )
    pos = jnp.asarray([bs * 3 - 1, 2, bs + 4], jnp.int32)

    out = paged_flash_decode(q, pk, pv, tables, pos, interpret=True)

    g = hq // hkv
    scale = d ** -0.5
    for i in range(b):
        rows_k = np.concatenate(
            [np.asarray(pk[tables[i, j]]) for j in range(mb)], axis=1
        )  # [Hkv, MB*bs, D]
        rows_v = np.concatenate(
            [np.asarray(pv[tables[i, j]]) for j in range(mb)], axis=1
        )
        mask = np.arange(mb * bs) <= int(pos[i])
        for h in range(hq):
            kv = h // g  # q reshape(b, hkv, g, d) is kv-major
            s = (np.asarray(q[i, h]) @ rows_k[kv].T) * scale
            s = np.where(mask, s, -np.inf)
            w = np.exp(s - s.max())
            w /= w.sum()
            want = w @ rows_v[kv]
            np.testing.assert_allclose(
                np.asarray(out[i, h]), want, rtol=2e-5, atol=2e-5,
                err_msg=f"slot {i} head {h}",
            )


@pytest.mark.slow
@pytest.mark.parametrize("block_size", [8, 16])
def test_pallas_server_parity(block_size):
    """Interpret-mode pallas path == gathered token-for-token through
    the full server (mixed sampling, prefix cache, re-admits). Slow:
    the interpreter walks the grid in Python."""
    dec = tiny_llama(64)
    params = dec.init(jax.random.key(0))
    reqs = _mixed_requests(dec.cfg.vocab_size)
    want, _ = _serve(
        dec, params, reqs, attention="gathered",
        block_size=block_size, prefix_cache=True,
    )
    got, stats = _serve(
        dec, params, reqs, attention="pallas",
        block_size=block_size, prefix_cache=True,
    )
    assert stats["attention"] == "pallas"
    for i, (a, b) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(
            a, b, err_msg=f"request {i} bs={block_size}"
        )
