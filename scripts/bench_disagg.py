#!/usr/bin/env python
"""Disaggregated-serving microbench: the same request mix through
monolithic `serve_paged` and split `serve_disagg` (prefill worker on a
loopback thread), printed as ONE JSON line.

The point being measured: the split buys placement freedom (prefill
and decode sized/scaled separately) at the price of shipping finished
KV state over the wire. This bench prices that wire: tokens/sec split
vs monolithic, mean TTFT (which now includes a network round trip),
and bytes-on-wire per request — lossless vs `quantize="int8"` KV
transfer (codec SCHEME_Q8), which is where the byte bill gets paid.

Standalone:

    JAX_PLATFORMS=cpu python scripts/bench_disagg.py
    python scripts/bench_disagg.py --no-int8 --requests 4

Importable: `run_microbench(devices) -> dict` — bench.py runs it as a
"disagg" extras section behind the supervisor/snapshot deadline
machinery, so a wedged worker cannot sink the headline.

Off-TPU the absolute tokens/sec is meaningless; the split/monolithic
ratio and the per-request wire bytes are the headline numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _ttft_state(reg) -> dict:
    snap = reg.value("defer_ttft_seconds", server="paged")
    return snap if snap else {"count": 0, "sum": 0.0}


def _ttft_mean_since(reg, before: dict) -> float | None:
    now = _ttft_state(reg)
    n = now["count"] - before["count"]
    return (now["sum"] - before["sum"]) / n if n else None


def run_microbench(
    devices=None,
    *,
    int8: bool = True,
    num_layers: int = 4,
    dim: int = 256,
    num_heads: int = 8,
    num_kv_heads: int = 4,
    vocab_size: int = 2048,
    max_len: int = 512,
    num_blocks: int = 49,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 6,
) -> dict:
    """Serve one fixed request mix monolithically and split; returns
    {config, monolithic: {...}, disagg: {...}, disagg_int8: {...}}.
    Deliberately small defaults — on CPU the interesting numbers are
    the split/monolithic ratio and the wire bytes, not throughput."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.disagg import serve_disagg
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.obs import get_registry
    from defer_tpu.runtime.paged import serve_paged

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    # float32 compute on purpose: bfloat16 KV travels as a lossless
    # uint16 view the Q8 codec skips (wire.to_wire_array), so a bf16
    # model would make the int8 variant a silent no-op.
    dec = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    prompt_tokens = sum(int(p.shape[1]) for p, _ in reqs)
    reg = get_registry()
    shared = dict(
        num_blocks=num_blocks,
        block_size=block_size,
        max_batch=max_batch,
    )

    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
            "prompt_tokens": prompt_tokens,
        },
    }

    def timed(serve):
        before = _ttft_state(reg)
        t0 = time.perf_counter()
        outs, stats = serve()
        jax.block_until_ready(outs[-1])
        dt = time.perf_counter() - t0
        ttft = _ttft_mean_since(reg, before)
        return dt, stats, ttft

    def mono():
        return serve_paged(dec, params, reqs, **shared)

    timed(mono)  # compile pass
    dt, stats, ttft = timed(mono)
    mono_tps = total_tokens / dt
    out["monolithic"] = {
        "tokens_per_sec": round(mono_tps, 1),
        "mean_ttft_s": round(ttft, 4) if ttft is not None else None,
        "ticks": stats["ticks"],
    }

    variants = [("disagg", None)] + ([("disagg_int8", "int8")] if int8 else [])
    lossless_bytes = None
    for key, quantize in variants:
        def split():
            return serve_disagg(
                dec, params, reqs, quantize=quantize, **shared
            )

        timed(split)  # compile pass (worker + decode paths)
        dt, stats, ttft = timed(split)
        tps = total_tokens / dt
        rec = {
            "tokens_per_sec": round(tps, 1),
            "split_vs_monolithic": round(tps / mono_tps, 3),
            "mean_ttft_s": round(ttft, 4) if ttft is not None else None,
            "ticks": stats["ticks"],
            "kv_bytes_recv": stats["kv_bytes_recv"],
            "kv_bytes_recv_per_request": int(
                stats["kv_bytes_recv_per_request"]
            ),
            "kv_bytes_per_prompt_token": round(
                stats["kv_bytes_recv"] / prompt_tokens, 1
            ),
            "dispatch_bytes_sent": stats["dispatch_bytes_sent"],
        }
        if quantize is None:
            lossless_bytes = stats["kv_bytes_recv"]
        elif lossless_bytes:
            rec["bytes_vs_lossless"] = round(
                stats["kv_bytes_recv"] / lossless_bytes, 3
            )
        out[key] = rec
    return out


def main() -> None:
    ap = argparse.ArgumentParser(
        description="disaggregated-serving microbench (one JSON line)"
    )
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--max-len", type=int, default=512)
    ap.add_argument("--blocks", type=int, default=49)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument(
        "--no-int8",
        action="store_true",
        help="skip the quantize='int8' KV-transfer variant",
    )
    args = ap.parse_args()
    rec = run_microbench(
        int8=not args.no_int8,
        num_layers=args.layers,
        dim=args.dim,
        num_heads=args.heads,
        num_kv_heads=args.kv_heads,
        vocab_size=args.vocab,
        max_len=args.max_len,
        num_blocks=args.blocks,
        block_size=args.block_size,
        max_batch=args.batch,
        num_requests=args.requests,
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
