#!/bin/bash
# The static-analysis CI gate, one command: strict lint + perf-contract
# budgets over the serving package. Exit code is the gate verdict:
#   0  clean (suppressions all justified; contracts pass or no-data)
#   1  findings — a hazard landed without a reason, or a declared
#      budget is violated by the newest BENCH_*.json (or $2)
#   2  usage/config error (malformed budgets.toml, bad path)
#
# Usage: scripts/analyze_gate.sh [OUT_JSON] [BENCH_JSON]
#   OUT_JSON    where to write the JSON report (default: stdout)
#   BENCH_JSON  bench artifact for the measured half (default: the
#               newest BENCH_*.json in the repo root)
set -u
cd "$(dirname "$0")/.."

out="${1:-}"
bench="${2:-}"

args=(--strict --json --budget budgets.toml defer_tpu/)
if [ -n "$bench" ]; then
  args+=(--bench "$bench")
fi

if [ -n "$out" ]; then
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m defer_tpu.analysis "${args[@]}" > "$out"
  rc=$?
  echo "analyze gate: rc=$rc report=$out" >&2
else
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m defer_tpu.analysis "${args[@]}"
  rc=$?
fi
exit $rc
