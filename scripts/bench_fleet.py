#!/usr/bin/env python
"""Fleet-serving stress microbench: a bursty, prefix-shared arrival
mix over N replica decode servers, printed as ONE JSON line.

Two questions priced here:

  1. Is cache locality a real routing signal? The same scripted
     workload — shared system prompts with Zipf reuse, bursty
     arrivals — runs through `policy="prefix"` and
     `policy="round_robin"`; the radix hit-rate gap between them is
     the entire value of the advertisement/digest machinery, and
     tokens/sec + TTFT p50/p99 show what the hit rate buys.
  2. Does overload degrade or collapse? A flood beyond aggregate
     capacity runs against a tight SLO + bounded queues; the headline
     is shed rate > 0 WITH the queue-wait p99 of admitted traffic
     bounded near the SLO (unbounded queueing would show p99 growing
     with the flood length instead).

Standalone:

    JAX_PLATFORMS=cpu python scripts/bench_fleet.py
    python scripts/bench_fleet.py --replicas 3 --requests 48

Importable: `run_microbench(devices) -> dict` — bench.py runs it as a
"fleet" extras section behind the supervisor/snapshot deadline
machinery.

Off-TPU the absolute tokens/sec is meaningless; the prefix-vs-rr hit
rate gap, the shed accounting, and the relative TTFT are the headline
numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _hist_state(reg, name: str, **labels) -> dict:
    snap = reg.value(name, **labels)
    return snap if snap else {"count": 0, "sum": 0.0, "buckets": []}


def _quantile_since(before: dict, after: dict, q: float) -> float | None:
    """Bucket-interpolated quantile of the observations recorded
    BETWEEN two histogram snapshots (the registry is cumulative, so a
    per-run quantile needs the bucket-count diff)."""
    n = after["count"] - before["count"]
    if n <= 0:
        return None
    b_cum = {e: c for e, c in before.get("buckets", [])}
    target = q * n
    lo = 0.0
    for edge, cum in after["buckets"]:
        d = cum - b_cum.get(edge, 0)
        if d >= target and edge != "+Inf":
            return float(edge)
        lo = edge if edge != "+Inf" else lo
    return float(lo) if lo else None


def _workload(
    rng, cfg, *, n_requests, n_sys, sys_len, suffix_max, steps_max
):
    """Prefix-shared request mix: each request is one of `n_sys`
    shared system prompts (Zipf-ish reuse: prompt 0 twice as popular
    as 1, etc.) plus a private suffix. sys_len is a block multiple so
    the shared region is exactly the radix-cacheable run."""
    import jax
    import jax.numpy as jnp

    sys_prompts = [
        jax.random.randint(
            jax.random.fold_in(jax.random.key(11), s),
            (1, sys_len), 0, cfg.vocab_size,
        )
        for s in range(n_sys)
    ]
    weights = [1.0 / (s + 1) for s in range(n_sys)]
    total_w = sum(weights)
    reqs = []
    for i in range(n_requests):
        u = rng.random() * total_w
        s = 0
        acc = weights[0]
        while acc < u and s < n_sys - 1:
            s += 1
            acc += weights[s]
        t_suf = 4 + int(rng.random() * (suffix_max - 4))
        suffix = jax.random.randint(
            jax.random.fold_in(jax.random.key(13), i),
            (1, t_suf), 0, cfg.vocab_size,
        )
        steps = 4 + int(rng.random() * (steps_max - 4))
        reqs.append((jnp.concatenate([sys_prompts[s], suffix], axis=1),
                     steps))
    return sys_prompts, reqs


def _drive(fe, reqs, *, burst: int, gap_s: float, paced: bool = False):
    """Bursty submission: `burst` requests back to back, then a gap,
    repeat. `paced=True` additionally waits for each burst's results
    before the next burst submits — prefills complete and the digest
    advertisements land, so routing sees the cache state the previous
    burst created (un-paced, every decision races the first compile and
    degenerates to load-routing). Returns (outputs in submission
    order, shed_count)."""
    from defer_tpu.fleet import ShedError

    outs = []
    shed = 0
    pending = []
    for i, (p, s) in enumerate(reqs):
        try:
            pending.append(fe.submit(p, s))
        except ShedError:
            shed += 1
        if (i + 1) % burst == 0:
            if paced:
                outs.extend(fe.result(g, timeout=600) for g in pending)
                pending = []
            time.sleep(gap_s)
    outs.extend(fe.result(g, timeout=600) for g in pending)
    return outs, shed


def run_microbench(
    devices=None,
    *,
    n_replicas: int = 2,
    num_layers: int = 2,
    dim: int = 128,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    vocab_size: int = 512,
    max_len: int = 256,
    num_blocks: int = 40,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 24,
    n_sys: int = 3,
    sys_len: int = 32,
    burst: int = 4,
    gap_s: float = 0.02,
    overload: bool = True,
) -> dict:
    """Run the prefix-shared workload under prefix-aware and
    round-robin routing, then (optionally) an overload flood against a
    tight SLO. Returns {config, prefix: {...}, round_robin: {...},
    overload: {...}}."""
    import random

    import jax
    import jax.numpy as jnp

    from defer_tpu.fleet import FleetFrontend
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.obs import get_registry

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])
    rng = random.Random(1234)
    sys_prompts, reqs = _workload(
        rng, cfg,
        n_requests=num_requests, n_sys=n_sys, sys_len=sys_len,
        suffix_max=24, steps_max=16,
    )
    total_tokens = sum(s for _, s in reqs)
    reg = get_registry()
    shared = dict(
        n_replicas=n_replicas,
        num_blocks=num_blocks,
        block_size=block_size,
        max_batch=max_batch,
        prefix_cache=True,
    )
    out: dict = {
        "config": {
            "replicas": n_replicas,
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "system_prompts": n_sys,
            "system_prompt_len": sys_len,
            "burst": burst,
            "total_tokens": total_tokens,
        },
    }

    # Warm the jit caches on the full request mix — the step/prefill
    # programs are memoized on the decoder and shared by every
    # frontend, so without this the first measured arm eats all the
    # compile time (every distinct prefill lane shape compiles).
    fe = FleetFrontend(dec, params, policy="prefix", **shared)
    try:
        _drive(fe, reqs, burst=burst, gap_s=0, paced=True)
    finally:
        fe.close()

    for policy in ("prefix", "round_robin"):
        fe = FleetFrontend(dec, params, policy=policy, **shared)
        hits0 = reg.value(
            "defer_prefix_cache_hits_total", server="paged"
        ) or 0
        miss0 = reg.value(
            "defer_prefix_cache_misses_total", server="paged"
        ) or 0
        ttft0 = _hist_state(reg, "defer_ttft_seconds", server="paged")
        t0 = time.perf_counter()
        try:
            outs, _ = _drive(
                fe, reqs, burst=burst, gap_s=gap_s, paced=True
            )
            jax.block_until_ready(outs[-1])
        finally:
            fe.close()
        dt = time.perf_counter() - t0
        hits = (reg.value(
            "defer_prefix_cache_hits_total", server="paged"
        ) or 0) - hits0
        miss = (reg.value(
            "defer_prefix_cache_misses_total", server="paged"
        ) or 0) - miss0
        ttft1 = _hist_state(reg, "defer_ttft_seconds", server="paged")
        stats = fe.stats()
        out[policy] = {
            "tokens_per_sec": round(total_tokens / dt, 1),
            "radix_hit_rate": round(hits / max(hits + miss, 1), 3),
            "prefix_hits": hits,
            "prefix_misses": miss,
            "ttft_p50_s": _quantile_since(ttft0, ttft1, 0.5),
            "ttft_p99_s": _quantile_since(ttft0, ttft1, 0.99),
            "routed": stats["routed"],
            "migrated_blocks": stats["migrated_blocks"],
            "shed": stats["shed"],
        }
    out["hit_rate_gain"] = round(
        out["prefix"]["radix_hit_rate"]
        - out["round_robin"]["radix_hit_rate"], 3,
    )

    if overload:
        # Flood well past aggregate capacity against a tight SLO and
        # short queues: the contract is shed > 0 AND the realized
        # queue-wait p99 of ADMITTED traffic staying bounded (the
        # rolling window the shedder itself reads).
        slo_s = 0.05
        fe = FleetFrontend(
            dec, params, policy="prefix",
            slo_s=slo_s, max_queue=2, **shared,
        )
        flood = [
            (r[0], r[1]) for r in reqs for _ in range(3)
        ]
        try:
            outs, shed = _drive(fe, flood, burst=len(flood), gap_s=0)
            p99s = [
                fe.controller.wait_p99(i) for i in range(n_replicas)
            ]
        finally:
            fe.close()
        out["overload"] = {
            "slo_s": slo_s,
            "offered": len(flood),
            "admitted": len(outs),
            "shed": shed,
            "shed_rate": round(shed / len(flood), 3),
            "shed_reasons": fe.stats()["shed"],
            "queue_wait_p99_s": [round(p, 4) for p in p99s],
        }
    return out


def main() -> None:
    ap = argparse.ArgumentParser(
        description="fleet-serving microbench (one JSON line)"
    )
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--blocks", type=int, default=40)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--sys-prompts", type=int, default=3)
    ap.add_argument("--sys-len", type=int, default=32)
    ap.add_argument("--burst", type=int, default=4)
    ap.add_argument(
        "--no-overload", action="store_true",
        help="skip the SLO/shedding flood section",
    )
    args = ap.parse_args()
    rec = run_microbench(
        n_replicas=args.replicas,
        num_layers=args.layers,
        dim=args.dim,
        num_heads=args.heads,
        num_kv_heads=args.kv_heads,
        vocab_size=args.vocab,
        num_blocks=args.blocks,
        block_size=args.block_size,
        max_batch=args.batch,
        num_requests=args.requests,
        n_sys=args.sys_prompts,
        sys_len=args.sys_len,
        burst=args.burst,
        overload=not args.no_overload,
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
