#!/usr/bin/env python
"""Paged-decode attention microbench: tokens/sec and estimated K/V
bytes read per tick for each attention mode, printed as ONE JSON line.

The point being measured: the gathered path's per-tick HBM traffic is
O(B * max_blocks * block_size) regardless of request depth, while the
block-native paths ("blockwise", "pallas") read only live blocks —
the new obs counters (defer_kv_rows_read_total vs the gathered
baseline) make the ratio exact, and this bench prices it per mode on
one identical request mix.

Standalone:

    JAX_PLATFORMS=cpu python scripts/bench_paged.py
    python scripts/bench_paged.py --modes gathered,blockwise,pallas

Importable: `run_microbench(devices) -> dict` — bench.py runs it as a
"paged_attention" extras section behind the supervisor/snapshot
deadline machinery, so a wedged compile cannot sink the headline.

Also here: `run_window_sweep(devices) -> dict` (`--window-sweep` on
the CLI) — the fused-decode-window sweep (decode_window = K in
{1,4,8,16}) pricing host dispatches per token against tokens/sec;
bench.py runs it as the "decode_window" extras section. And
`run_mixed_sweep(devices) -> dict` (`--mixed-sweep`) — the
mixed-mode continuous-batching sweep (prefill_budget = stall
baseline + {64,128,256,inf}, the same request mix offered open-loop
via runtime/batching.py::poisson_arrivals) pricing live slots' ITL
p50/p99, TTFT, tokens/sec and the decode-stall fraction per budget;
bench.py runs it as the "mixed_serving" extras section. And
`run_spec_sweep(devices) -> dict` (`--spec-sweep`) — the paged
speculative-decoding sweep (spec_k in {0,2,4} crossed with a DRAFT
AXIS: self | trunc:L/2 | trunc:L/4 | width:1/2, built with
models/transplant.py `make_draft`) pricing MEASURED acceptance,
tokens/sec and dispatches-per-token per (draft, k) — the
acceptance-vs-speedup frontier; bench.py runs it as the
"speculative" extras section. And `run_tp_sweep(devices) -> dict` (`--tp-sweep`) —
the tensor-parallel serving sweep (model_axis in {1,2,4,8} on a
{"model": m} mesh, runtime/paged.py `mesh=`) pricing tokens/sec,
tokens-per-dispatch and per-shard KV rows read per axis size;
bench.py runs it as the "tp_serving" extras section. And
`run_pp_sweep(devices) -> dict` (`--pp-sweep`) — the
pipeline-parallel serving sweep (pp_stages S in {1,2,4} crossed with
in-flight microbatch counts M, runtime/paged.py `pp_stages=`) pricing
tokens/sec, the MEASURED bubble fraction and per-stage occupancy of
the dispatch-slot schedule, and per-stage KV-pool bytes (~1/S each);
bench.py runs it as the "pp_serving" extras section. And
`run_kv_quant_sweep(devices) -> dict` (`--kv-quant-sweep`) — the
KV-quantization sweep (kv_dtype fp vs int8 over the same
over-subscribed Zipf prefix mix with the host-RAM spill tier on)
pricing tokens/sec, resident-requests-per-pool-MiB and the spill
revival rate; bench.py runs it as the "kv_quant" extras section. And
`run_constrain_sweep(devices) -> dict` (`--constrain-sweep`) — the
constrained-decoding sweep (defer_tpu/constrain/: the same request
mix served free vs regex-constrained vs JSON-schema-constrained)
pricing the on-device DFA mask fold (tokens/sec vs the free
baseline), host compile time, DFA table size and the mean
masked-vocabulary fraction; bench.py runs it as the "constrain"
extras section.

"pallas" is excluded by default off-TPU: the interpret-mode kernel is
functionally identical but interpreter-slow, which would price the
mode's dispatch overhead, not its bandwidth. Pass --modes to force it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_DEFAULT_MODES = ("gathered", "blockwise")


def _native_pallas() -> bool:
    from defer_tpu.ops.attention import _pallas_available

    return _pallas_available()


def run_microbench(
    devices=None,
    *,
    modes: tuple = (),
    num_layers: int = 4,
    dim: int = 256,
    num_heads: int = 8,
    num_kv_heads: int = 4,
    vocab_size: int = 2048,
    max_len: int = 512,
    num_blocks: int = 49,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 8,
) -> dict:
    """Serve one fixed request mix through every attention mode;
    returns {config, modes: {mode: {tokens_per_sec, kv_rows_read,
    kv_rows_gathered_baseline, kv_read_ratio, est_kv_bytes_per_tick,
    ...}}}. Deliberately small defaults: the ratio, not the absolute
    throughput, is the headline off-TPU."""
    import jax
    import jax.numpy as jnp

    from defer_tpu import obs
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.paged import serve_paged

    if not modes:
        modes = _DEFAULT_MODES + (
            ("pallas",) if _native_pallas() else ()
        )
    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    dh = cfg.dim // cfg.num_heads
    # Bytes behind one counted row unit: K+V, every layer, all KV
    # heads (the counters are layer/head-agnostic; obs/serving.py).
    bytes_per_row = (
        2 * cfg.num_layers * cfg.kv_heads * dh
        * jnp.dtype(dec.compute_dtype).itemsize
    )

    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
        },
        "modes": {},
    }
    lab = 'server="paged"'
    for mode in modes:
        def run():
            t0 = time.perf_counter()
            with obs.counter_deltas() as d:
                outs, stats = serve_paged(
                    dec,
                    params,
                    reqs,
                    num_blocks=num_blocks,
                    block_size=block_size,
                    max_batch=max_batch,
                    attention=mode,
                )
                jax.block_until_ready(outs[-1])
            return time.perf_counter() - t0, d, stats
        run()  # compile pass
        dt, deltas, stats = run()
        rows = deltas.get(f"defer_kv_rows_read_total{{{lab}}}", 0)
        base = deltas.get(
            f"defer_kv_rows_gathered_baseline_total{{{lab}}}", 0
        )
        ticks = max(1, stats["ticks"])
        out["modes"][mode] = {
            "tokens_per_sec": round(total_tokens / dt, 1),
            "ticks": stats["ticks"],
            "kv_rows_read": rows,
            "kv_rows_gathered_baseline": base,
            "kv_read_ratio": round(rows / max(1, base), 4),
            "est_kv_bytes_per_tick": int(
                rows / ticks * bytes_per_row
            ),
            "est_kv_bytes_per_tick_gathered": int(
                base / ticks * bytes_per_row
            ),
        }
    return out


def run_window_sweep(
    devices=None,
    *,
    windows: tuple = (1, 4, 8, 16),
    num_layers: int = 4,
    dim: int = 256,
    num_heads: int = 8,
    num_kv_heads: int = 4,
    vocab_size: int = 2048,
    max_len: int = 512,
    num_blocks: int = 49,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 8,
) -> dict:
    """Fused-decode-window sweep: the same fixed request mix served at
    decode_window = K for each K, through the paged server's gathered
    path. Returns {config, windows: {K: {tokens_per_sec,
    host_dispatches, dispatches_per_token, tokens_per_dispatch,
    speedup_vs_k1}}}. The point being measured: every decode token
    costs one host dispatch at K=1; a window of K amortizes that fixed
    dispatch overhead over up to K tokens, so dispatches-per-token
    falls toward 1/K and small-model tokens/sec — dominated by
    dispatch overhead, not math — climbs with it."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.paged import serve_paged

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
        },
        "windows": {},
    }
    base_tps = None
    for K in windows:
        def run():
            t0 = time.perf_counter()
            outs, stats = serve_paged(
                dec,
                params,
                reqs,
                num_blocks=num_blocks,
                block_size=block_size,
                max_batch=max_batch,
                decode_window=K,
            )
            jax.block_until_ready(outs[-1])
            return time.perf_counter() - t0, stats
        run()  # compile pass
        dt, stats = run()
        tps = total_tokens / dt
        if base_tps is None:
            base_tps = tps
        out["windows"][K] = {
            "tokens_per_sec": round(tps, 1),
            "host_dispatches": stats["host_dispatches"],
            "dispatches_per_token": round(
                stats["host_dispatches"] / total_tokens, 4
            ),
            "tokens_per_dispatch": round(
                stats["tokens_per_dispatch"], 2
            ),
            "speedup_vs_k1": round(tps / base_tps, 3),
        }
    return out


def run_mixed_sweep(
    devices=None,
    *,
    budgets: tuple = (64, 128, 256, "inf"),
    arrival_rate: float = 16.0,
    arrival_seed: int = 0,
    num_layers: int = 4,
    dim: int = 256,
    num_heads: int = 8,
    num_kv_heads: int = 4,
    vocab_size: int = 2048,
    max_len: int = 512,
    num_blocks: int = 49,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 12,
) -> dict:
    """Mixed-mode continuous-batching sweep: the same request mix
    offered OPEN-LOOP (runtime/batching.py::poisson_arrivals — a fixed
    seeded arrival trace that does not throttle itself when the server
    falls behind), served with prefill_budget = None (the stall
    baseline: every admission prefill preempts decode) and each value
    in `budgets` ("inf" = effectively unbounded). Returns {config,
    budgets: {stall|64|...|inf: {itl_p50_ms, itl_p99_ms, ttft_mean_ms,
    ttft_p95_ms, tokens_per_sec, prefill_stall_ticks, mixed_ticks,
    mixed_prefill_tokens, decode_stall_fraction}}}.

    The point being measured: with stall-mode admission, a prompt
    arriving mid-decode freezes every live slot for its whole prefill
    — the freeze lands directly in the live slots' inter-token
    latency tail (ITL p99). Mixed mode fuses up to `budget` prompt
    tokens into each decode dispatch, so decode never skips a tick
    and the p99 collapses toward the p50; the budget knob then trades
    TTFT (bigger chunks land prompts sooner) against per-tick decode
    latency (wider fused T costs more per dispatch)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.batching import poisson_arrivals
    from defer_tpu.runtime.paged import PagedDecodeServer

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    arrivals = poisson_arrivals(
        num_requests, arrival_rate, seed=arrival_seed
    )

    def run_point(budget):
        stamps: dict = {}

        def on_token(rid, tok, done):
            stamps.setdefault(rid, []).append(time.perf_counter())

        srv = PagedDecodeServer(
            dec,
            params,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch=max_batch,
            prefill_budget=budget,
            on_token=on_token,
        )
        submit_at: dict = {}
        nxt = 0
        t0 = time.perf_counter()
        while nxt < len(reqs) or srv.pending or any(
            s is not None for s in srv.slots
        ):
            now = time.perf_counter() - t0
            while nxt < len(reqs) and arrivals[nxt] <= now:
                rid = srv.submit(*reqs[nxt])
                submit_at[rid] = time.perf_counter()
                nxt += 1
            srv._admit()
            if any(s is not None for s in srv.slots):
                srv._tick()
            elif nxt < len(reqs):
                # Open-loop idle gap: nothing seated, next arrival
                # still in the future — sleep toward it instead of
                # spinning admit hot.
                time.sleep(
                    min(
                        5e-4,
                        max(
                            0.0,
                            arrivals[nxt]
                            - (time.perf_counter() - t0),
                        ),
                    )
                )
        dt = time.perf_counter() - t0
        gaps = [
            g
            for ts in stamps.values()
            for g in np.diff(ts)
            if len(ts) >= 2
        ]
        ttfts = [
            ts[0] - submit_at[rid] for rid, ts in stamps.items()
        ]
        return {
            "itl_p50_ms": round(
                float(np.percentile(gaps, 50)) * 1e3, 3
            ),
            "itl_p99_ms": round(
                float(np.percentile(gaps, 99)) * 1e3, 3
            ),
            "ttft_mean_ms": round(
                float(np.mean(ttfts)) * 1e3, 3
            ),
            "ttft_p95_ms": round(
                float(np.percentile(ttfts, 95)) * 1e3, 3
            ),
            "tokens_per_sec": round(total_tokens / dt, 1),
            "prefill_stall_ticks": srv.prefill_stall_ticks_n,
            "mixed_ticks": srv.mixed_ticks_n,
            "mixed_prefill_tokens": srv.mixed_prefill_tokens_n,
            "decode_stall_fraction": round(
                srv.decode_stall_fraction_last, 4
            ),
        }

    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
            "arrival_rate_rps": arrival_rate,
            "arrival_seed": arrival_seed,
        },
        "budgets": {},
    }
    # "inf" = a budget no single tick can exhaust: admission-window
    # prompts land as fast as chunk_cap/t_limit allow.
    points = [("stall", None)] + [
        (str(b), max_len if b == "inf" else int(b)) for b in budgets
    ]
    for key, budget in points:
        run_point(budget)  # compile pass
        out["budgets"][key] = run_point(budget)
    return out


def run_spec_sweep(
    devices=None,
    *,
    ks: tuple = (0, 2, 4),
    drafts: tuple = ("self", "trunc:L/2", "trunc:L/4", "width:1/2"),
    num_layers: int = 4,
    dim: int = 64,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    vocab_size: int = 512,
    max_len: int = 256,
    num_blocks: int = 49,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 8,
    decode_window: int = 1,
    late_scale: float = 0.25,
) -> dict:
    """Paged speculative-decoding sweep over a DRAFT AXIS: the same
    fixed request mix served at spec_k = k for each k and each draft
    construction (0 = the classic tick loop, the shared baseline).
    Returns {config, baseline, drafts: {label: {geometry, ks: {k:
    {tokens_per_sec, acceptance, spec_rounds, host_dispatches,
    dispatches_per_token, draft_tokens, speedup_vs_k0}}}}, ks} where
    the top-level `ks` keeps the old self-draft table shape
    (baseline row at 0) for existing readers.

    The draft axis is the acceptance-vs-speedup frontier: `self`
    (draft IS the target — acceptance 1.0, isolating the pure
    dispatch-amortization term), `trunc:L/2` / `trunc:L/4`
    (layer-truncated via models/transplant.py `make_draft(layers=)` —
    the residual stream after the shared prefix layers still
    correlates with the full forward, so acceptance lands BETWEEN 0
    and 1 and the sweep measures a real frontier point), and
    `width:1/2` (head/FFN-pruned via `make_draft(width=)`). Each
    draft's `acceptance` is MEASURED, not assumed; speculation wins
    exactly where `(1 + acceptance*k) / 2 > 1` dispatch-for-dispatch
    and the draft's forward is cheap enough to not eat the margin.

    `decode_window=W>1` prices the fused spec x window path: W whole
    draft+verify rounds per host dispatch (dispatches_per_token drops
    by ~W on top of the round amortization).

    `late_scale` shrinks the residual WRITE (wo/w2 + biases) of the
    late half of the target's stack after init. Trained checkpoints
    concentrate most of the logit-relevant residual mass in early
    layers — that is the property layer truncation banks on — but
    random init spreads it uniformly, which would price every real
    draft at acceptance ~ 0 and measure nothing. The shrink restores
    the trained-model shape; acceptance is still MEASURED, never
    assumed (set late_scale=1.0 to see the uniform-init floor).

    Defaults are deliberately SMALLER than the other sweeps':
    speculation only pays where per-dispatch overhead dominates
    compute — the regime small drafts / big targets occupy on real
    hardware, emulated here by shrinking the model."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.models.transplant import make_draft
    from defer_tpu.runtime.paged import serve_paged

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.init(jax.random.key(0))
    if late_scale != 1.0 and num_layers > 1:
        half = num_layers // 2
        ramp = jnp.asarray(
            [1.0 if l < half else late_scale for l in range(num_layers)]
        )
        st = dict(params["stack"])
        for key in ("wo", "w2"):
            st[key] = st[key] * ramp[:, None, None]
        for key in ("bo", "b2"):
            if key in st:
                st[key] = st[key] * ramp[:, None]
        params = {**params, "stack": st}
    params = dec.cast_params(params)
    if devices:
        params = jax.device_put(params, devices[0])
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)

    def build_draft(label):
        """label -> (draft decoder, draft params). `trunc:L/n` slices
        the first num_layers//n layers; `width:p/q` prunes heads+FFN
        to the fraction p/q; `self` reuses the target."""
        if label == "self":
            return dec, params
        kind, _, arg = label.partition(":")
        if kind == "trunc":
            den = int(arg.split("/")[1])
            return make_draft(
                dec, params, layers=max(1, num_layers // den)
            )
        if kind == "width":
            num, den = arg.split("/")
            return make_draft(dec, params, width=float(num) / float(den))
        raise ValueError(f"unknown draft axis label {label!r}")

    def timed(**kwargs):
        def run():
            t0 = time.perf_counter()
            outs, stats = serve_paged(
                dec,
                params,
                reqs,
                num_blocks=num_blocks,
                block_size=block_size,
                max_batch=max_batch,
                decode_window=decode_window,
                **kwargs,
            )
            jax.block_until_ready(outs[-1])
            return time.perf_counter() - t0, stats

        run()  # compile pass
        return run()

    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
            "decode_window": decode_window,
            "drafts": list(drafts),
        },
        "drafts": {},
    }
    dt, stats = timed()
    base_tps = total_tokens / dt
    baseline = {
        "tokens_per_sec": round(base_tps, 1),
        "acceptance": 0.0,
        "spec_rounds": 0,
        "host_dispatches": stats["host_dispatches"],
        "dispatches_per_token": round(
            stats["host_dispatches"] / total_tokens, 4
        ),
        "draft_tokens": 0,
        "speedup_vs_k0": 1.0,
    }
    out["baseline"] = baseline
    for label in drafts:
        draft, dparams = build_draft(label)
        dcfg = draft.cfg
        per: dict = {
            "geometry": (
                f"{dcfg.num_layers}L/{dcfg.num_heads}h/"
                f"{dcfg.dim}d/{dcfg.ffn_dim}f"
            ),
            "ks": {},
        }
        for k in ks:
            if not k:
                continue
            dt, stats = timed(
                spec_draft=draft, spec_params=dparams, spec_k=k
            )
            tps = total_tokens / dt
            per["ks"][k] = {
                "tokens_per_sec": round(tps, 1),
                "acceptance": round(stats["spec_acceptance"], 4),
                "spec_rounds": stats["spec_rounds"],
                "host_dispatches": stats["host_dispatches"],
                "dispatches_per_token": round(
                    stats["host_dispatches"] / total_tokens, 4
                ),
                "draft_tokens": stats["spec_draft_tokens"],
                "speedup_vs_k0": round(tps / base_tps, 3),
            }
        out["drafts"][label] = per
    # Old table shape (self-draft, baseline at k=0) for readers that
    # predate the draft axis.
    if "self" in out["drafts"]:
        out["ks"] = {0: baseline, **out["drafts"]["self"]["ks"]}
    return out


def run_tp_sweep(
    devices=None,
    *,
    axes: tuple = (1, 2, 4, 8),
    num_layers: int = 4,
    dim: int = 256,
    num_heads: int = 8,
    num_kv_heads: int = 8,
    vocab_size: int = 2048,
    max_len: int = 512,
    num_blocks: int = 49,
    block_size: int = 16,
    max_batch: int = 4,
    num_requests: int = 8,
) -> dict:
    """Tensor-parallel serving sweep: the same fixed request mix served
    on a {"model": m} mesh for each axis size m that fits the visible
    devices (CPU runs force 8 host devices via XLA_FLAGS, the test
    rig's idiom). Returns {config, device_kind, axes: {m:
    {tokens_per_sec, host_dispatches, dispatches_per_token,
    tokens_per_dispatch, kv_rows_read_per_shard, kv_rows_scaling,
    tp_psums, mesh_shape}}}.

    The points being measured: host dispatches per token must NOT move
    with m (one dispatch drives all shards — the contract the
    counter-pinned test enforces), per-shard KV rows read must fall as
    1/m (each shard owns kv_heads/m heads of every block), and
    tokens/sec prices what the psum/all-gather chatter costs on this
    interconnect. `num_kv_heads` defaults to 8 so every swept axis
    divides it."""
    import jax
    import jax.numpy as jnp

    from defer_tpu import obs
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.parallel.mesh import describe_topology, make_mesh
    from defer_tpu.runtime.paged import serve_paged

    devs = list(devices) if devices else jax.devices()
    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    topo = describe_topology()
    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
        },
        "device_kind": topo["device_kind"],
        "num_devices": len(devs),
        "skipped_axes": [m for m in axes if m > len(devs)],
        "axes": {},
    }
    base_rows = None
    for m in axes:
        if m > len(devs):
            continue
        mesh = make_mesh({"model": m}, devs[:m])
        mesh_shape = f"model={m}"
        lab = f'mesh="{mesh_shape}",server="paged"'

        def run():
            t0 = time.perf_counter()
            with obs.counter_deltas() as d:
                outs, stats = serve_paged(
                    dec,
                    params,
                    reqs,
                    num_blocks=num_blocks,
                    block_size=block_size,
                    max_batch=max_batch,
                    mesh=mesh,
                )
                jax.block_until_ready(outs[-1])
            return time.perf_counter() - t0, d, stats

        run()  # compile pass
        dt, deltas, stats = run()
        rows = deltas.get(f"defer_kv_rows_read_total{{{lab}}}", 0)
        if base_rows is None:
            base_rows = rows
        out["axes"][m] = {
            "tokens_per_sec": round(total_tokens / dt, 1),
            "host_dispatches": stats["host_dispatches"],
            "dispatches_per_token": round(
                stats["host_dispatches"] / total_tokens, 4
            ),
            "tokens_per_dispatch": round(
                stats["tokens_per_dispatch"], 2
            ),
            "kv_rows_read_per_shard": rows,
            "kv_rows_scaling": round(rows / max(1, base_rows), 4),
            "tp_psums": stats["tp_psums"],
            "mesh_shape": mesh_shape,
        }
    return out


def run_pp_sweep(
    devices=None,
    *,
    grid: tuple = ((1, 1), (2, 2), (4, 2), (4, 4)),
    decode_window: int = 8,
    num_layers: int = 4,
    dim: int = 128,
    num_heads: int = 4,
    num_kv_heads: int = 4,
    vocab_size: int = 1024,
    max_len: int = 256,
    num_blocks: int = 33,
    block_size: int = 8,
    max_batch: int = 4,
    num_requests: int = 8,
) -> dict:
    """Pipeline-parallel serving sweep: the same fixed request mix
    served with the layer stack cut into S stages (one device and one
    KV-pool slice per stage) at M in-flight microbatch groups, for
    each (S, M) in `grid`. Returns {config, device_kind, num_devices,
    skipped, grid: {"s{S}_m{M}": {tokens_per_sec, speedup_vs_s1,
    bubble_fraction, stage_occupancy, stage_dispatches,
    stage_pool_bytes, pool_bytes_vs_s1, cut_starts}}} — keys are
    flat "s2_m2" strings so budgets.toml bench_metric paths can
    navigate them.

    The points being measured: bubble_fraction is the MEASURED idle
    share of the dispatch-slot schedule (runtime/batching.py
    `pp_schedule_occupancy` over what the tick actually dispatched,
    last window) — (S-1)/(S-1 + chains) when every group stays live,
    shrinking as M and decode_window amortize the fill/drain ramps;
    per-stage pool bytes must sum to ~the S=1 pool (each stage holds
    ONLY its layers' slice); and tokens/sec prices the overlap.
    Wall-clock speedup needs real parallel hardware — stages on forced
    host devices share the machine's cores, so on a small CPU rig the
    schedule metrics, not tokens/sec, carry the claim (the ROADMAP's
    standing caution about absolute CPU numbers applies doubly here).
    (S, M) points needing more devices than visible are skipped and
    reported; M never exceeds max_batch."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.parallel.mesh import describe_topology
    from defer_tpu.runtime.paged import serve_paged

    devs = list(devices) if devices else jax.devices()
    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    reqs = []
    for i in range(num_requests):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    topo = describe_topology()
    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
            "decode_window": decode_window,
        },
        "device_kind": topo["device_kind"],
        "num_devices": len(devs),
        "skipped": [
            f"s{s}_m{m}"
            for s, m in grid
            if s > len(devs) or m > max_batch or max_batch % m
        ],
        "grid": {},
    }
    base_tps = None
    base_pool = None
    for s, m in grid:
        if s > len(devs) or m > max_batch or max_batch % m:
            continue
        pp = (
            {}
            if s == 1
            else {
                "pp_stages": s,
                "pp_inflight": m,
                "pp_devices": devs[:s],
            }
        )

        def run():
            t0 = time.perf_counter()
            outs, stats = serve_paged(
                dec,
                params,
                reqs,
                num_blocks=num_blocks,
                block_size=block_size,
                max_batch=max_batch,
                decode_window=decode_window,
                **pp,
            )
            jax.block_until_ready(outs[-1])
            return time.perf_counter() - t0, stats

        run()  # compile pass
        dt, stats = run()
        tps = total_tokens / dt
        if s == 1:
            base_tps = tps
            base_pool = stats["pool_bytes"]
        out["grid"][f"s{s}_m{m}"] = {
            "tokens_per_sec": round(tps, 1),
            "speedup_vs_s1": round(
                tps / base_tps if base_tps else 0.0, 3
            ),
            "bubble_fraction": round(stats["pp_bubble_fraction"], 4),
            "stage_occupancy": [
                round(o, 4) for o in stats["pp_stage_occupancy"]
            ],
            "stage_dispatches": stats["pp_stage_dispatches"],
            "stage_pool_bytes": stats["pp_stage_pool_bytes"],
            "pool_bytes_vs_s1": round(
                stats["pool_bytes"] / base_pool if base_pool else 0.0, 4
            ),
            "cut_starts": stats["pp_cut_starts"],
        }
    return out


def run_kv_quant_sweep(
    devices=None,
    *,
    dtypes: tuple = ("fp", "int8"),
    num_layers: int = 2,
    dim: int = 64,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    vocab_size: int = 512,
    max_len: int = 256,
    num_blocks: int = 17,
    block_size: int = 4,
    max_batch: int = 2,
    num_requests: int = 12,
    num_prefixes: int = 4,
    prefix_len: int = 16,
    spill_bytes: int = 32 << 20,
) -> dict:
    """KV-quantization sweep: the same over-subscribed Zipf-prefix
    request mix served with a fp pool vs an int8+scales pool, both with
    the host-RAM spill tier on. Returns {config, dtypes: {d:
    {tokens_per_sec, pool_bytes, pool_bytes_vs_fp,
    resident_requests_per_pool_mib, spilled_blocks, spill_hits,
    spill_revival_rate, prefill_tokens, prefill_tokens_no_spill,
    prefill_tokens_saved}}}.

    The request mix is Zipf-ish over `num_prefixes` shared prefixes
    (popularity ~ 1/rank), dealt round-robin so a popular prefix's next
    request arrives only after the other prefixes' traffic has pushed
    its cached blocks out of the deliberately undersized pool — the
    over-subscription that makes eviction (and hence spill) happen at
    all. Three things are being priced: (1) capacity — int8 stores the
    same blocks in itemsize-fold fewer bytes (4x under fp32 compute,
    2x under this sweep's bf16, plus per-[layer,block,head] scales),
    so resident-requests-per-pool-MiB is the headline ratio; (2) the
    spill tier — spilled_blocks / spill_hits under pressure, with
    prefill_tokens vs the spill_bytes=0 baseline showing the prefill
    rows the revivals saved; (3) throughput — tokens/sec, which off-TPU
    mostly prices dispatch overhead (the HBM-bandwidth win needs real
    hardware; the obs row counters are dtype-agnostic by design).

    spill_revival_rate is spill_hits / spilled_blocks — the fraction of
    evicted-and-spilled blocks a later request actually revived (> 0 is
    the acceptance bar; ~1 means the spill store is doing real work)."""
    import jax
    import jax.numpy as jnp

    from defer_tpu import obs
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.paged import serve_paged

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])

    # Zipf-ish popularity: prefix r gets ~1/(r+1) of the traffic.
    weights = [1.0 / (r + 1) for r in range(num_prefixes)]
    wsum = sum(weights)
    counts = [
        max(1, round(num_requests * w / wsum)) for w in weights
    ]
    while sum(counts) > num_requests:
        counts[counts.index(max(counts))] -= 1
    while sum(counts) < num_requests:
        counts[0] += 1
    prefixes = [
        jax.random.randint(
            jax.random.fold_in(jax.random.key(7), r),
            (1, prefix_len),
            0,
            cfg.vocab_size,
        )
        for r in range(num_prefixes)
    ]
    # Deal round-robin: a prefix's next request lands only after the
    # other prefixes' traffic had a chance to evict its blocks.
    order = []
    for j in range(max(counts)):
        for r in range(num_prefixes):
            if counts[r] > j:
                order.append(r)
    reqs = []
    for i, r in enumerate(order):
        tail = 2 + (i * 3) % 4
        steps = 12 + (i * 7) % 12
        suffix = jax.random.randint(
            jax.random.fold_in(jax.random.key(11), i),
            (1, tail),
            0,
            cfg.vocab_size,
        )
        reqs.append((jnp.concatenate([prefixes[r], suffix], axis=1), steps))
    total_tokens = sum(s for _, s in reqs)
    # Mean per-request footprint in blocks, for the capacity metric.
    blocks_per_req = sum(
        -(-(p.shape[1] + s) // block_size) for p, s in reqs
    ) / len(reqs)
    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
            "prefix_mix": f"zipf({num_prefixes})x{prefix_len}tok",
            "spill_bytes": spill_bytes,
        },
        "dtypes": {},
    }
    lab = 'server="paged"'
    fp_pool_bytes = None
    for d in dtypes:

        def run(spill):
            t0 = time.perf_counter()
            with obs.counter_deltas() as deltas:
                outs, stats = serve_paged(
                    dec,
                    params,
                    reqs,
                    num_blocks=num_blocks,
                    block_size=block_size,
                    max_batch=max_batch,
                    prefix_cache=True,
                    kv_dtype=d,
                    spill_bytes=spill,
                )
                jax.block_until_ready(outs[-1])
            return time.perf_counter() - t0, deltas, stats

        run(spill_bytes)  # compile pass
        dt, deltas, stats = run(spill_bytes)
        _, base_deltas, _ = run(0)  # no-spill baseline: same mix
        if fp_pool_bytes is None:
            fp_pool_bytes = stats["pool_bytes"]
        prefill = deltas.get(f"defer_prefill_tokens_total{{{lab}}}", 0)
        prefill_base = base_deltas.get(
            f"defer_prefill_tokens_total{{{lab}}}", 0
        )
        spilled = deltas.get(f"defer_prefix_spilled_total{{{lab}}}", 0)
        out["dtypes"][d] = {
            "tokens_per_sec": round(total_tokens / dt, 1),
            "pool_bytes": stats["pool_bytes"],
            "pool_bytes_vs_fp": round(
                stats["pool_bytes"] / fp_pool_bytes, 4
            ),
            "resident_requests_per_pool_mib": round(
                ((num_blocks - 1) / blocks_per_req)
                / (stats["pool_bytes"] / (1 << 20)),
                2,
            ),
            "spilled_blocks": spilled,
            "spill_hits": stats["spill_hits"],
            "spill_revival_rate": round(
                stats["spill_hits"] / max(1, spilled), 4
            ),
            "prefill_tokens": prefill,
            "prefill_tokens_no_spill": prefill_base,
            "prefill_tokens_saved": stats["prefill_tokens_saved"],
        }
    return out


def run_constrain_sweep(
    devices=None,
    *,
    modes: tuple = ("free", "regex", "json"),
    decode_window: int = 1,
    num_layers: int = 2,
    dim: int = 64,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    vocab_size: int = 128,
    max_len: int = 256,
    num_blocks: int = 33,
    block_size: int = 4,
    max_batch: int = 4,
    num_requests: int = 8,
) -> dict:
    """Constrained-decoding sweep (defer_tpu/constrain/): the same
    request mix served three ways — free (constraints registered but
    no request opts in: the pre-constraint programs must dispatch),
    regex-constrained (`[0-9]+(\\.[0-9]+)?`), and JSON-schema-
    constrained (an object with a boolean and a bounded integer
    array) — each at `decode_window` sub-steps per host dispatch.
    Returns {config, constraints: {mode: {tokens_per_sec,
    tps_vs_free, constrained_tokens, mean_masked_frac, dead_ends,
    compile_ms, dfa_states, dfa_table_kib}}}.

    Two prices being measured: (1) the host compiler — regex ->
    char DFA -> token lift -> dead-state prune, a one-off cost per
    (pattern, vocab) reported in compile_ms with the resulting
    stacked-table footprint (dfa_states, dfa_table_kib); (2) the
    device mask fold — one [B] gather + where + argmax riding the
    existing tick, so tps_vs_free near 1.0 is the acceptance bar
    (off-TPU the gap prices dispatch, not bandwidth). The vocabulary
    is synthetic char-level text (digits, letters, JSON punctuation,
    a few multi-char merges exercising the token lift), sized to the
    model's `vocab_size`; mean_masked_frac says how much of that
    vocabulary the grammar removed per emitted token — near 1.0
    means the DFA, not the model, is doing the choosing."""
    import jax
    import jax.numpy as jnp

    from defer_tpu import obs
    from defer_tpu.constrain import compile_json_schema, compile_regex
    from defer_tpu.models.gpt import GptDecoder, SamplingParams
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.paged import serve_paged

    # Char-level vocabulary: id 0 is the empty string and doubles as
    # eos; then chars the constraints below can spell, a few
    # multi-char merges (the token-lift cases), filler to size.
    chars = list(
        "0123456789abcdefghijklmnopqrstuvwxyz"
        "{}[]\",:.- eE+ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    )
    vocab = [""] + chars + ["ab", "12", '":', "},", "true", "false"]
    if len(vocab) > vocab_size:
        raise ValueError(
            f"vocab_size {vocab_size} too small for the "
            f"{len(vocab)}-token constraint vocabulary"
        )
    vocab += [f"<u{i}>" for i in range(vocab_size - len(vocab))]

    pattern = r"[0-9]+(\.[0-9]+)?"
    schema = {
        "type": "object",
        "properties": {
            "ok": {"type": "boolean"},
            "ids": {
                "type": "array",
                "items": {"type": "integer"},
                "minItems": 1,
                "maxItems": 3,
            },
        },
    }
    compiled = {}
    for name, build in (
        ("regex", lambda: compile_regex(pattern, vocab)),
        ("json", lambda: compile_json_schema(schema, vocab)),
    ):
        t0 = time.perf_counter()
        dfa = build()
        compiled[name] = (dfa, (time.perf_counter() - t0) * 1e3)
    constraints = {n: d for n, (d, _) in compiled.items()}

    cfg = llama_config(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=dim * 2,
        vocab_size=vocab_size,
        max_len=max_len,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = dec.cast_params(dec.init(jax.random.key(0)))
    if devices:
        params = jax.device_put(params, devices[0])
    reqs = []
    for i in range(num_requests):
        t0 = 4 + (i * 5) % 12
        steps = 16 + (i * 7) % 16
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            1,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))
    total_tokens = sum(s for _, s in reqs)
    out: dict = {
        "config": {
            "num_layers": num_layers,
            "dim": dim,
            "heads": f"{num_heads}/{num_kv_heads}kv",
            "vocab_size": vocab_size,
            "max_len": max_len,
            "num_blocks": num_blocks,
            "block_size": block_size,
            "max_batch": max_batch,
            "requests": num_requests,
            "total_tokens": total_tokens,
            "decode_window": decode_window,
            "pattern": pattern,
        },
        "constraints": {},
    }
    reg = obs.get_registry()
    frac_key = dict(server="paged")
    free_tps = None
    for mode in modes:
        sp = (
            None
            if mode == "free"
            else SamplingParams(constraint=mode)
        )

        def run():
            before = reg.value(
                "defer_constrain_masked_frac", **frac_key
            ) or {"count": 0, "sum": 0.0}
            t0 = time.perf_counter()
            outs, stats = serve_paged(
                dec,
                params,
                reqs,
                num_blocks=num_blocks,
                block_size=block_size,
                max_batch=max_batch,
                eos_id=0,
                decode_window=decode_window,
                constraints=constraints,
                sampling=[sp] * len(reqs),
            )
            jax.block_until_ready(outs[-1])
            dt = time.perf_counter() - t0
            after = reg.value(
                "defer_constrain_masked_frac", **frac_key
            ) or {"count": 0, "sum": 0.0}
            dcount = after["count"] - before["count"]
            dsum = after["sum"] - before["sum"]
            return dt, stats, (dsum / dcount if dcount else 0.0)

        run()  # compile pass
        dt, stats, mean_frac = run()
        # Constrained streams stop at eos when the grammar is
        # satisfied, so normalize throughput by tokens actually
        # emitted, not the step budget.
        emitted = stats["constrained_tokens"] or total_tokens
        tps = emitted / dt
        if mode == "free":
            free_tps = tps
        rec = {
            "tokens_per_sec": round(tps, 1),
            "tps_vs_free": round(
                tps / free_tps if free_tps else 0.0, 3
            ),
            "constrained_tokens": stats["constrained_tokens"],
            "mean_masked_frac": round(mean_frac, 4),
            "dead_ends": stats["constraint_dead_ends"],
        }
        if mode in compiled:
            dfa, ms = compiled[mode]
            rec.update(
                compile_ms=round(ms, 2),
                dfa_states=dfa.num_states,
                dfa_table_kib=round(
                    dfa.transitions.nbytes / 1024, 1
                ),
            )
        out["constraints"][mode] = rec
    return out


def main() -> None:
    ap = argparse.ArgumentParser(
        description="paged-decode attention microbench (one JSON line)"
    )
    ap.add_argument(
        "--modes",
        default="",
        help="comma-separated subset of gathered,blockwise,pallas "
        "(default: gathered,blockwise; +pallas on native TPU)",
    )
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--max-len", type=int, default=512)
    ap.add_argument("--blocks", type=int, default=49)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument(
        "--window-sweep",
        action="store_true",
        help="run the fused-decode-window sweep (decode_window = "
        "--windows) instead of the attention-mode microbench",
    )
    ap.add_argument(
        "--windows",
        default="1,4,8,16",
        help="comma-separated decode_window values for --window-sweep",
    )
    ap.add_argument(
        "--mixed-sweep",
        action="store_true",
        help="run the mixed-mode continuous-batching sweep "
        "(prefill_budget = stall baseline + --mixed-budgets, "
        "open-loop Poisson arrivals) instead of the attention "
        "microbench",
    )
    ap.add_argument(
        "--mixed-budgets",
        default="64,128,256,inf",
        help="comma-separated prefill_budget values for "
        "--mixed-sweep (inf = unbounded; the stall baseline is "
        "always included)",
    )
    ap.add_argument(
        "--mixed-rate",
        type=float,
        default=16.0,
        help="open-loop arrival rate (requests/sec) for "
        "--mixed-sweep",
    )
    ap.add_argument(
        "--spec-sweep",
        action="store_true",
        help="run the paged speculative-decoding sweep (spec_k = "
        "--spec-ks crossed with the --spec-drafts draft axis) "
        "instead of the attention microbench",
    )
    ap.add_argument(
        "--spec-ks",
        default="0,2,4",
        help="comma-separated spec_k values for --spec-sweep "
        "(0 = non-speculative baseline)",
    )
    ap.add_argument(
        "--spec-drafts",
        default="self,trunc:L/2,trunc:L/4,width:1/2",
        help="comma-separated draft constructions for --spec-sweep: "
        "self (acceptance 1), trunc:L/n (layer-truncated via "
        "make_draft), width:p/q (head/FFN-pruned)",
    )
    ap.add_argument(
        "--spec-window",
        type=int,
        default=1,
        help="decode_window for --spec-sweep (W>1 prices the fused "
        "spec x window path: W rounds per host dispatch)",
    )
    ap.add_argument(
        "--kv-quant-sweep",
        action="store_true",
        help="run the KV-quantization sweep (kv_dtype = --kv-dtypes, "
        "over-subscribed Zipf prefix mix with the spill tier on) "
        "instead of the attention microbench",
    )
    ap.add_argument(
        "--kv-dtypes",
        default="fp,int8",
        help="comma-separated kv_dtype values for --kv-quant-sweep",
    )
    ap.add_argument(
        "--constrain-sweep",
        action="store_true",
        help="run the constrained-decoding sweep (the same request "
        "mix served free vs regex- vs JSON-schema-constrained, "
        "defer_tpu/constrain/) instead of the attention microbench",
    )
    ap.add_argument(
        "--constrain-modes",
        default="free,regex,json",
        help="comma-separated subset of free,regex,json for "
        "--constrain-sweep",
    )
    ap.add_argument(
        "--constrain-window",
        type=int,
        default=1,
        help="decode_window for --constrain-sweep (W>1 prices the "
        "constrained fused-window path)",
    )
    ap.add_argument(
        "--pp-sweep",
        action="store_true",
        help="run the pipeline-parallel serving sweep (pp_stages x "
        "in-flight microbatches = --pp-grid; points needing more "
        "devices than visible are skipped and reported) instead of "
        "the attention microbench",
    )
    ap.add_argument(
        "--pp-grid",
        default="s1_m1,s2_m2,s4_m2,s4_m4",
        help="comma-separated s{S}_m{M} points for --pp-sweep",
    )
    ap.add_argument(
        "--pp-window",
        type=int,
        default=8,
        help="decode_window for --pp-sweep (W rounds ride inside "
        "each in-flight microbatch, amortizing the pipeline ramps)",
    )
    ap.add_argument(
        "--tp-sweep",
        action="store_true",
        help="run the tensor-parallel serving sweep (model_axis = "
        "--tp-axes, axes that exceed the visible devices are skipped "
        "and reported) instead of the attention microbench",
    )
    ap.add_argument(
        "--tp-axes",
        default="1,2,4,8",
        help="comma-separated model-axis sizes for --tp-sweep",
    )
    args = ap.parse_args()
    shared = dict(
        num_layers=args.layers,
        dim=args.dim,
        num_heads=args.heads,
        num_kv_heads=args.kv_heads,
        vocab_size=args.vocab,
        max_len=args.max_len,
        num_blocks=args.blocks,
        block_size=args.block_size,
        max_batch=args.batch,
        num_requests=args.requests,
    )
    if args.kv_quant_sweep:
        # Same default-dropping as --spec-sweep: the sweep's own model
        # and (deliberately undersized) pool defaults win unless a
        # flag was explicitly overridden.
        arg_of = {
            "num_layers": "layers",
            "dim": "dim",
            "num_heads": "heads",
            "num_kv_heads": "kv_heads",
            "vocab_size": "vocab",
            "max_len": "max_len",
            "num_blocks": "blocks",
            "block_size": "block_size",
            "max_batch": "batch",
            "num_requests": "requests",
        }
        shared = {
            k: v
            for k, v in shared.items()
            if v != ap.get_default(arg_of[k])
        }
        dtypes = tuple(d for d in args.kv_dtypes.split(",") if d)
        rec = run_kv_quant_sweep(dtypes=dtypes, **shared)
    elif args.constrain_sweep:
        # Same default-dropping as --spec-sweep: the sweep's own tiny
        # char-vocab model defaults win unless a flag was explicitly
        # overridden.
        arg_of = {
            "num_layers": "layers",
            "dim": "dim",
            "num_heads": "heads",
            "num_kv_heads": "kv_heads",
            "vocab_size": "vocab",
            "max_len": "max_len",
            "num_blocks": "blocks",
            "block_size": "block_size",
            "max_batch": "batch",
            "num_requests": "requests",
        }
        shared = {
            k: v
            for k, v in shared.items()
            if v != ap.get_default(arg_of[k])
        }
        modes = tuple(
            m for m in args.constrain_modes.split(",") if m
        )
        rec = run_constrain_sweep(
            modes=modes,
            decode_window=args.constrain_window,
            **shared,
        )
    elif args.pp_sweep:
        # Same default-dropping as --spec-sweep: run_pp_sweep's own
        # (smaller) model defaults win unless a flag was explicitly
        # overridden.
        arg_of = {
            "num_layers": "layers",
            "dim": "dim",
            "num_heads": "heads",
            "num_kv_heads": "kv_heads",
            "vocab_size": "vocab",
            "max_len": "max_len",
            "num_blocks": "blocks",
            "block_size": "block_size",
            "max_batch": "batch",
            "num_requests": "requests",
        }
        shared = {
            k: v
            for k, v in shared.items()
            if v != ap.get_default(arg_of[k])
        }
        grid = []
        for pt in args.pp_grid.split(","):
            if not pt:
                continue
            s_part, _, m_part = pt.strip().partition("_")
            grid.append((int(s_part.lstrip("s")), int(m_part.lstrip("m"))))
        rec = run_pp_sweep(
            grid=tuple(grid), decode_window=args.pp_window, **shared
        )
    elif args.tp_sweep:
        # Same default-dropping as --spec-sweep: run_tp_sweep's own
        # model defaults (kv_heads=8 so every axis divides) win unless
        # a flag was explicitly overridden.
        arg_of = {
            "num_layers": "layers",
            "dim": "dim",
            "num_heads": "heads",
            "num_kv_heads": "kv_heads",
            "vocab_size": "vocab",
            "max_len": "max_len",
            "num_blocks": "blocks",
            "block_size": "block_size",
            "max_batch": "batch",
            "num_requests": "requests",
        }
        shared = {
            k: v
            for k, v in shared.items()
            if v != ap.get_default(arg_of[k])
        }
        axes = tuple(int(m) for m in args.tp_axes.split(",") if m)
        rec = run_tp_sweep(axes=axes, **shared)
    elif args.spec_sweep:
        # Let run_spec_sweep's own (smaller) model defaults win unless
        # the user explicitly overrode a flag: entries still at the
        # parser default are dropped.
        arg_of = {
            "num_layers": "layers",
            "dim": "dim",
            "num_heads": "heads",
            "num_kv_heads": "kv_heads",
            "vocab_size": "vocab",
            "max_len": "max_len",
            "num_blocks": "blocks",
            "block_size": "block_size",
            "max_batch": "batch",
            "num_requests": "requests",
        }
        shared = {
            k: v
            for k, v in shared.items()
            if v != ap.get_default(arg_of[k])
        }
        ks = tuple(int(k) for k in args.spec_ks.split(",") if k)
        drafts = tuple(d for d in args.spec_drafts.split(",") if d)
        rec = run_spec_sweep(
            ks=ks,
            drafts=drafts,
            decode_window=args.spec_window,
            **shared,
        )
    elif args.mixed_sweep:
        budgets = tuple(
            b if b == "inf" else int(b)
            for b in args.mixed_budgets.split(",")
            if b
        )
        rec = run_mixed_sweep(
            budgets=budgets, arrival_rate=args.mixed_rate, **shared
        )
    elif args.window_sweep:
        windows = tuple(
            int(k) for k in args.windows.split(",") if k
        )
        rec = run_window_sweep(windows=windows, **shared)
    else:
        modes = tuple(m for m in args.modes.split(",") if m)
        rec = run_microbench(modes=modes, **shared)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
