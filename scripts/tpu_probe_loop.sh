#!/bin/bash
# Background TPU-evidence capture loop (VERDICT r4 item #1).
# Retries a cheap TPU probe; on success runs bench.py and stamps
# BENCH_TPU_LKG.json with git sha + timestamp. Exits after first success.
cd /root/repo
for i in $(seq 1 60); do
  echo "[probe $i] $(date -u +%FT%TZ)" >> /tmp/tpu_probe.log
  if timeout 90 python - <<'EOF' >> /tmp/tpu_probe.log 2>&1
import os
os.environ['JAX_PLATFORMS'] = 'tpu'
import jax
d = jax.devices()[0]
assert d.platform == 'tpu', d.platform
import jax.numpy as jnp
x = jnp.ones((128, 128))
(x @ x).block_until_ready()
print('TPU OK:', d)
EOF
  then
    echo "[probe $i] TPU alive — running bench" >> /tmp/tpu_probe.log
    if DEFER_BENCH_NO_FALLBACK=1 timeout 2400 python bench.py \
        > /tmp/bench_tpu_try.out 2>> /tmp/tpu_probe.log; then
      python - <<'EOF' > /tmp/tpu_stamp.out 2>&1
import json, subprocess, datetime
with open('/tmp/bench_tpu_try.out') as f:
    lines = [l for l in f.read().strip().splitlines() if l.strip()]
data = json.loads(lines[-1])
if data.get('platform') == 'tpu' and data.get('value'):
    data['git_sha'] = subprocess.check_output(['git', 'rev-parse', 'HEAD'], text=True).strip()
    data['timestamp'] = datetime.datetime.now(datetime.timezone.utc).isoformat()
    with open('BENCH_TPU_LKG.json', 'w') as f:
        json.dump(data, f, indent=1)
    print('WROTE BENCH_TPU_LKG.json')
else:
    print('bench ran but not a TPU result:', data.get('platform'), data.get('value'))
EOF
      cat /tmp/tpu_stamp.out >> /tmp/tpu_probe.log
      # Gate on the stamping step actually writing a FRESH record — a
      # pre-existing file must not end the loop.
      if grep -q "WROTE BENCH_TPU_LKG.json" /tmp/tpu_stamp.out; then
        echo "SUCCESS $(date -u +%FT%TZ)" >> /tmp/tpu_probe.log
        exit 0
      fi
    fi
  fi
  sleep 600
done
echo "EXHAUSTED $(date -u +%FT%TZ)" >> /tmp/tpu_probe.log
exit 1
