#!/usr/bin/env python
"""Autoregressive generation demo: KV-cache decode, optionally
tensor-parallel.

    # single device
    python examples/generate.py --steps 32
    # tensor-parallel over an emulated 4-device mesh
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/generate.py --tp 4 --steps 32

Prints prefill latency, per-token decode latency, and tokens/sec —
the numbers a serving deployment cares about. (Random weights: the
tokens are noise; the machinery is the demo.)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from defer_tpu.utils.platform import honor_env_platform

honor_env_platform()

import argparse
import time

import jax
import jax.numpy as jnp

from defer_tpu.models.gpt import GptDecoder, SpmdGptDecoder
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.transformer_stack import TransformerConfig


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--ffn", type=int, default=2048)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--max-len", type=int, default=512)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--min-p", type=float, default=0.0)
    ap.add_argument("--rep-penalty", type=float, default=1.0)
    ap.add_argument(
        "--family",
        choices=("gpt", "llama"),
        default="gpt",
        help="llama = RMSNorm + rotary + grouped-query attention + "
        "SwiGLU (biasless), with the KV cache sized by --kv-heads",
    )
    ap.add_argument(
        "--kv-heads",
        type=int,
        default=None,
        help="GQA kv head count, any family (llama default: heads/4; "
        "gpt default: MHA)",
    )
    ap.add_argument(
        "--speculate",
        type=int,
        default=0,
        metavar="K",
        help="after the plain loop, run greedy speculative decoding "
        "with a 1-layer draft proposing K tokens per target forward "
        "(needs --tp 1 --batch 1)",
    )
    args = ap.parse_args()

    if args.prompt_len + args.steps + 1 > args.max_len:
        raise SystemExit(
            f"--prompt-len {args.prompt_len} + --steps {args.steps} + 1 "
            f"exceeds --max-len {args.max_len}: the cache would clamp and "
            "benchmark degenerate work"
        )

    if args.family == "llama":
        from defer_tpu.models.llama import llama_config

        cfg = llama_config(
            num_layers=args.layers,
            dim=args.dim,
            num_heads=args.heads,
            num_kv_heads=args.kv_heads or max(1, args.heads // 4),
            ffn_dim=args.ffn,
            vocab_size=args.vocab,
            max_len=args.max_len,
        )
    else:
        # GQA is a shared-stack knob, not llama-exclusive: honor
        # --kv-heads here too instead of silently ignoring it.
        cfg = TransformerConfig(
            num_layers=args.layers,
            dim=args.dim,
            num_heads=args.heads,
            num_kv_heads=args.kv_heads,
            ffn_dim=args.ffn,
            vocab_size=args.vocab,
            max_len=args.max_len,
            norm_style="pre",
        )
    # Serving storage: params in the compute dtype (decode reads every
    # weight per token — fp32 storage would double the HBM traffic).
    if args.tp > 1:
        mesh = make_mesh({"model": args.tp}, jax.devices()[: args.tp])
        dec = SpmdGptDecoder(cfg, mesh=mesh)
        params = dec.shard_params(dec.cast_params(dec.init(jax.random.key(0))))
        print(f"tensor-parallel decode over {args.tp} devices "
              f"({jax.devices()[0].device_kind})")
    else:
        dec = GptDecoder(cfg)
        params = dec.cast_params(dec.init(jax.random.key(0)))
        print(f"single-device decode ({jax.devices()[0].device_kind})")

    prompt = jax.random.randint(
        jax.random.key(1), (args.batch, args.prompt_len), 0, args.vocab
    )
    step = dec.make_step()
    cache = dec.init_cache(args.batch)

    t0 = time.perf_counter()
    logits, cache = step(params, cache, prompt)
    logits.block_until_ready()
    t_prefill_compile = time.perf_counter() - t0

    from defer_tpu.models.gpt import (
        repetition_penalty,
        sample_token,
        seen_tokens_mask,
    )

    rng = jax.random.key(7)
    seen = (
        seen_tokens_mask(prompt, logits.shape[-1])
        if args.rep_penalty != 1.0
        else None
    )

    def pick(logits_last, rng, seen):
        lg = logits_last[:, -1, :]
        if seen is not None:
            lg = repetition_penalty(lg, seen, args.rep_penalty)
        tok, rng = sample_token(
            lg,
            rng,
            args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            min_p=args.min_p,
        )
        if seen is not None:
            seen = seen.at[jnp.arange(tok.shape[0]), tok].set(True)
        return tok[:, None].astype(prompt.dtype), rng, seen

    nxt, rng, seen = pick(logits, rng, seen)
    t0 = time.perf_counter()
    logits, cache = step(params, cache, nxt)
    logits.block_until_ready()
    t_decode_compile = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(args.steps):
        nxt, rng, seen = pick(logits, rng, seen)
        logits, cache = step(params, cache, nxt)
    logits.block_until_ready()
    dt = time.perf_counter() - t0

    per_tok = dt / args.steps
    print(
        f"prefill({args.prompt_len} tok) incl. compile: "
        f"{t_prefill_compile * 1e3:.0f} ms; decode compile: "
        f"{t_decode_compile * 1e3:.0f} ms"
    )
    print(
        f"steady decode: {per_tok * 1e3:.2f} ms/token, "
        f"{args.batch / per_tok:,.1f} tokens/sec"
        f" (batch {args.batch})"
    )

    if args.speculate and args.tp == 1 and args.batch == 1:
        if args.rep_penalty != 1.0:
            print(
                "note: --rep-penalty is not applied on the speculative "
                "path (its acceptance math covers the filtered softmax "
                "policy only), so the two decodes sample different "
                "policies"
            )
        import dataclasses

        from defer_tpu.models.speculative import speculative_generate

        # Draft shape: derive heads first, then round dim up to a
        # multiple so the head split always divides.
        d_heads = max(1, args.heads // 4)
        d_dim = -(-max(32, args.dim // 4) // d_heads) * d_heads
        draft_cfg = dataclasses.replace(
            cfg, num_layers=1, dim=d_dim,
            num_heads=d_heads,
            num_kv_heads=None,
            ffn_dim=max(64, args.ffn // 4),
        )
        draft = GptDecoder(draft_cfg)
        dparams = draft.cast_params(draft.init(jax.random.key(1)))
        keep = cfg.max_len - args.steps - args.speculate
        if keep < 1:
            raise SystemExit(
                f"--speculate {args.speculate} + --steps {args.steps} "
                f"leaves no prompt room in --max-len {cfg.max_len}"
            )
        short = prompt[:, : min(args.prompt_len, keep)]
        t0 = time.perf_counter()
        out, stats = speculative_generate(
            dec, params, draft, dparams, short, args.steps,
            k=args.speculate,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            min_p=args.min_p,
        )
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        print(
            f"speculative (k={args.speculate}, 1-layer random draft): "
            f"{stats['target_steps']} target forwards for "
            f"{stats['plain_steps']} tokens, acceptance "
            f"{stats['acceptance']:.2f}, {dt / args.steps * 1e3:.2f} "
            "ms/token incl. compile (random drafts agree rarely; a "
            "trained draft is where the win comes from)"
        )
    elif args.speculate:
        print("--speculate needs --tp 1 and --batch 1; skipped")


if __name__ == "__main__":
    main()
