#!/usr/bin/env python
"""LoRA fine-tuning, end to end: freeze a base model, train adapters
only, checkpoint them, merge, and serve the merged model.

The full adapter lifecycle on the SPMD machinery:

  1. build a "pretrained" base (random here; swap in a transplanted
     checkpoint in practice) with a lora_rank > 0 config;
  2. train ONLY the adapter factors + task head on a synthetic
     classification task (the optimizer state is adapter-sized, the
     base tree is never touched) — over a dp x pp mesh, with FSDP
     optionally sharding the frozen base weights too;
  3. save the adapter-only tree (what a fine-tune actually ships);
  4. merge w + scale * a @ b and run the merged, adapter-free model.

    python examples/finetune_lora.py --steps 30 --rank 8 --fsdp
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import tempfile
import time


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--alpha", type=float, default=16.0)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--fsdp", action="store_true",
                    help="shard the frozen base weights over the data "
                    "axis too (all-gathered per block)")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import numpy as np
    import optax

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.parallel.lora import (
        combine_lora,
        make_lora_train_step,
        merge_lora,
    )
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig
    from defer_tpu.runtime.checkpoint import load_checkpoint, save_checkpoint

    devs = jax.devices()
    axes = {"data": 2, "stage": 2} if len(devs) >= 4 else {"stage": 1}
    mesh = make_mesh(axes, devs[: max(1, 2 * axes.get("data", 1))])

    cfg = TransformerConfig(
        num_layers=args.layers, dim=args.dim, num_heads=args.heads,
        ffn_dim=args.ffn, vocab_size=args.vocab, max_len=64,
        lora_rank=args.rank, lora_alpha=args.alpha,
        lora_targets=("wq", "wv", "w1", "w2"),
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32, fsdp=args.fsdp)
    init_state, train_step = make_lora_train_step(
        sb, optax.adam(args.lr), num_classes=args.classes
    )
    state, base = init_state(jax.random.key(0))

    n_train = sum(
        x.size for x in jax.tree_util.tree_leaves(state.params)
    )
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(base))
    print(
        f"trainable {n_train:,} params ({100 * n_train / n_base:.2f}% "
        f"of the {n_base:,}-param frozen base), mesh={axes}"
        + (", base FSDP-sharded" if args.fsdp else "")
    )

    # Synthetic task: class = hash bucket of the first token.
    mb, b, s = 2, 4, 16
    ids = jax.random.randint(
        jax.random.key(1), (mb, b, s), 0, args.vocab
    )
    labels = ids[..., 0] % args.classes

    t0 = time.perf_counter()
    losses = []
    for _ in range(args.steps):
        state, loss = train_step(state, base, ids, labels)
        losses.append(float(loss))
    dt = time.perf_counter() - t0
    print(
        f"{args.steps} adapter steps in {dt:.2f}s: loss "
        f"{losses[0]:.3f} -> {losses[-1]:.3f}"
    )
    assert losses[-1] < losses[0], "fine-tune failed to reduce loss"

    # Ship the adapters: checkpoint only the trainable tree.
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "adapters.ckpt")
        save_checkpoint(path, state.params)
        size_kb = os.path.getsize(path) / 1024
        restored = load_checkpoint(path)
    print(f"adapter checkpoint: {size_kb:.1f} KiB (base not included)")

    # Merge for serving: adapter-free tree at base-model cost.
    tuned = combine_lora(base, restored)
    merged = merge_lora(tuned, cfg)
    cfg0 = TransformerConfig(
        num_layers=args.layers, dim=args.dim, num_heads=args.heads,
        ffn_dim=args.ffn, vocab_size=args.vocab, max_len=64,
    )
    sb0 = SpmdBert(mesh, cfg0, compute_dtype=jnp.float32)
    pooled = sb0.make_step()(
        {k: v for k, v in merged.items() if not k.startswith("cls_")},
        ids,
    )
    logits = (
        np.asarray(pooled, np.float32) @ np.asarray(restored["cls_w"])
        + np.asarray(restored["cls_b"])
    )
    acc = float((logits.argmax(-1) == np.asarray(labels)).mean())
    print(f"merged-model train accuracy: {acc:.2f}")
    assert acc > 0.5, "merged model lost the fine-tune"
    print("finetune_lora OK")


if __name__ == "__main__":
    main()
