#!/usr/bin/env python
"""Distributed pipeline-inference driver — the reference's `test.py`
user model, TPU-native (reference src/test.py:20-58).

Where the reference hard-codes two compute-node IPs and ships sub-models
over sockets, this discovers the TPU slice and pins jit-compiled stages
to cores; the queue-in/queue-out contract and the cut-list knob are
unchanged, so a reference user's driver ports line for line.

    python examples/distributed_infer.py --model resnet50 --minutes 1
    python examples/distributed_infer.py --cuts add_2,add_4,add_6,add_8
    python examples/distributed_infer.py --images examples/images

Inputs are real decoded images (PIL -> preprocess -> batch -> device
prefetch), cycled for the duration of the run — the reference's
image-feed loop (reference src/test.py:13-16,52-54) with a directory
instead of one hard-coded JPEG. --synthetic feeds jnp.ones instead.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import jax

# Honor an explicit platform choice even when site customization
# pre-imported jax with another backend registered.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import argparse
import itertools
import queue
import threading
import time

import jax.numpy as jnp

from defer_tpu.api import DEFER
from defer_tpu.models import get_model
from defer_tpu.runtime.data import (
    batched,
    imagenet_preprocess,
    load_image_dir,
    prefetch_to_device,
    preprocess_mode,
)

def image_stream(images_dir: str, model, batch: int):
    """Decode -> preprocess -> batch -> device-prefetch, cycling the
    directory forever (static shapes; prefetch overlaps host decode +
    transfer with device compute)."""
    mode = preprocess_mode(model.name)
    size = model.input_shape[0]

    def examples():
        for im in itertools.cycle(load_image_dir(images_dir)):
            # bf16 on the host: halves the host->device transfer and
            # matches the pipeline compute dtype (no device cast pass).
            yield imagenet_preprocess(
                im, size=size, mode=mode, out_dtype=jnp.bfloat16
            )[0]

    return prefetch_to_device(batched(examples(), batch))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument(
        "--cuts",
        default=None,
        help="comma-separated cut layers (reference test.py's part_at), "
        "or 'auto' for FLOPs-balanced boundaries; default: one stage "
        "per visible device from the model's candidate list",
    )
    ap.add_argument("--minutes", type=float, default=5.0)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument(
        "--images",
        default=os.path.join(os.path.dirname(__file__), "images"),
        help="directory of images to cycle through the pipeline",
    )
    ap.add_argument(
        "--synthetic",
        action="store_true",
        help="feed jnp.ones instead of decoding real images",
    )
    ap.add_argument(
        "--weights",
        default=None,
        help='real checkpoint for the pipeline: "imagenet", "random", '
        "or a Keras save_weights .h5 path (default: fresh init, as the "
        "throughput numbers don't depend on the values)",
    )
    args = ap.parse_args()

    model = get_model(args.model)
    params = None
    if args.weights:
        from defer_tpu.models.pretrained import (
            PretrainedUnavailable,
            load_pretrained,
        )

        from defer_tpu.models.transplant import TransplantError

        try:
            model, params, _ = load_pretrained(args.model, args.weights)
            print(f"{args.model}: weights from {args.weights}")
        except PretrainedUnavailable as e:
            print(f"pretrained weights unavailable ({e}); using fresh init")
        except TransplantError as e:
            raise SystemExit(
                f"checkpoint did not match the {args.model} graph: {e}"
            ) from e
    n_dev = len(jax.devices())
    if args.cuts == "auto":
        cuts = "auto"
        print(f"{args.model}: auto (FLOPs-balanced) stages over "
              f"{n_dev} device(s)")
    else:
        cuts = (
            args.cuts.split(",")
            if args.cuts
            else model.default_cuts(min(n_dev, len(model.cut_candidates) + 1))
        )
        print(f"{args.model}: {len(cuts) + 1} stages over {n_dev} device(s)")

    defer = DEFER()
    # The reference sizes these 10 deep for backpressure (test.py:44-45).
    input_q: queue.Queue = queue.Queue(10)
    output_q: queue.Queue = queue.Queue()
    if args.synthetic:
        x = model.example_input(args.batch)
        feed = itertools.repeat(x)
    else:
        feed = image_stream(args.images, model, args.batch)

    run_s = args.minutes * 60
    start = time.time()

    def print_result(q: queue.Queue) -> None:
        res_count = 0
        while q.get() is not None:
            res_count += 1
        images = res_count * args.batch
        print(f"{res_count} results in {args.minutes} min")
        print(f"Throughput: {images / (time.time() - start):.2f} images/sec")
        if defer.last_stage_latencies:
            for r in defer.last_stage_latencies:
                print(
                    f"  stage {r['stage']}: p50 {r['p50_s'] * 1e3:.2f} ms "
                    f"max {r['max_s'] * 1e3:.2f} ms"
                )

    a = threading.Thread(
        target=defer.run_defer, args=(model, cuts, input_q, output_q),
        kwargs={"params": params},
        daemon=True,
    )
    b = threading.Thread(target=print_result, args=(output_q,))
    a.start()
    b.start()

    try:
        while (time.time() - start) < run_s:
            # blocks at depth 10 — backpressure, as in test.py:52
            input_q.put(next(feed))
    finally:
        # Always flow the sentinels, even when the image feed raises —
        # otherwise the result thread blocks on output_q forever and
        # the process never exits.
        input_q.put(None)
        # Join the pipeline thread before exiting: tearing the
        # interpreter down mid-compile crashes XLA, and run_defer
        # drains in-flight results on the way out.
        a.join()
        output_q.put(None)
        b.join()


if __name__ == "__main__":
    main()
