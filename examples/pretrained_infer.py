#!/usr/bin/env python
"""Pretrained-weights end-to-end demo: real Keras ResNet50 checkpoint
-> transplant -> classify real images -> single-device and pipelined
runs must agree on top-1 (reference src/local_infer.py:8-23).

    python examples/pretrained_infer.py                    # imagenet (cache/net)
    python examples/pretrained_infer.py --weights PATH.h5  # local checkpoint
    python examples/pretrained_infer.py --weights random   # offline: real
        tf.keras model with fresh weights; still proves the transplant
        numerically by cross-checking against TF's own forward.

With no network, no ~/.keras cache and no --weights, the demo SKIPS
cleanly (exit 0, "SKIP" line) instead of half-running.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import queue
import threading

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument(
        "--weights",
        default="imagenet",
        help='"imagenet", "random", or a Keras save_weights .h5 path',
    )
    ap.add_argument(
        "--images",
        default=os.path.join(os.path.dirname(__file__), "images"),
    )
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument(
        "--model-json",
        default=None,
        help="model.to_json() text file — required to resolve layer "
        "names in Keras 3 .weights.h5 checkpoints",
    )
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from defer_tpu.models.pretrained import (
        PretrainedUnavailable,
        load_pretrained,
    )
    from defer_tpu.models.transplant import TransplantError

    try:
        model, params, tf_model = load_pretrained(
            args.model, args.weights, model_json=args.model_json
        )
    except PretrainedUnavailable as e:
        print(f"SKIP: pretrained weights unavailable ({e})")
        return 0
    except TransplantError as e:
        print(
            f"ERROR: checkpoint did not match the {args.model} graph "
            f"({e}). Keras 3 .weights.h5 files need --model-json "
            "<file containing model.to_json()>."
        )
        return 2

    from defer_tpu.runtime.data import (
        imagenet_preprocess,
        load_image_dir,
        preprocess_mode,
    )

    names, imgs = [], []
    for fname, arr in load_image_dir(args.images, with_names=True):
        names.append(fname)
        imgs.append(arr)
    if not imgs:
        print(f"SKIP: no images in {args.images}")
        return 0
    # imagenet_preprocess returns NHWC; one image in -> (1,H,W,C) out.
    batch = np.concatenate(
        [
            imagenet_preprocess(
                a,
                size=model.input_shape[0],
                mode=preprocess_mode(model.name),
                out_dtype=np.float32,
            )
            for a in imgs
        ]
    )

    # 1. Single-device forward.
    y_single = np.asarray(model.graph.apply(params, batch))
    top1_single = y_single.argmax(-1)

    # 2. The same params streamed through the distributed pipeline
    #    (queue-in/queue-out contract, reference src/test.py:30-41).
    from defer_tpu.api import DEFER

    defer = DEFER()
    cuts = model.default_cuts(args.stages)
    inq: queue.Queue = queue.Queue()
    outq: queue.Queue = queue.Queue()
    t = threading.Thread(
        target=defer.run_defer,
        args=(model, cuts, inq, outq),
        kwargs={"params": params},
        daemon=True,
    )
    t.start()
    inq.put(batch)
    inq.put(None)
    y_pipe = np.asarray(outq.get(timeout=600))
    t.join(timeout=120)
    top1_pipe = y_pipe.argmax(-1)

    assert (top1_single == top1_pipe).all(), (
        f"top-1 disagreement: single {top1_single} vs pipeline {top1_pipe}"
    )

    # 3. Cross-check against tf.keras' own forward when it is live.
    if tf_model is not None:
        y_tf = np.asarray(tf_model(batch, training=False))
        top1_tf = y_tf.argmax(-1)
        assert (top1_single == top1_tf).all(), (
            f"top-1 disagreement vs tf.keras: {top1_single} vs {top1_tf}"
        )

    labels = _imagenet_labels()
    for n, idx, p in zip(names, top1_single, y_single.max(-1)):
        label = labels[idx] if labels else f"class {idx}"
        print(f"{n}: top-1 {label} (index {idx}, p={p:.3f})")
    agree = "single==pipeline" + ("==tf.keras" if tf_model is not None else "")
    print(
        f"OK: {len(names)} images, {len(cuts) + 1}-stage pipeline, "
        f"top-1 agreement {agree}"
    )
    return 0


def _imagenet_labels() -> list[str] | None:
    """Class names if keras' imagenet_class_index.json is cached
    locally; None offline (indices are printed instead)."""
    try:
        from tensorflow.keras.applications.imagenet_utils import (
            decode_predictions,
        )

        one_hot = np.zeros((1, 1000), np.float32)
        one_hot[0, 0] = 1.0
        decode_predictions(one_hot, top=1)  # trigger the index load
        from tensorflow.keras.applications import imagenet_utils

        index = imagenet_utils.CLASS_INDEX
        return [index[str(i)][1] for i in range(1000)]
    except Exception:  # noqa: BLE001 — offline / no TF
        return None


if __name__ == "__main__":
    raise SystemExit(main())
