#!/usr/bin/env python
"""Single-device baseline — the reference's `local_infer.py`, TPU-native
(reference src/local_infer.py:16-23: loop model.predict, count results).

This defines the denominator of every pipeline speedup claim.

    python examples/local_infer.py --model resnet50 --minutes 1
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import jax

# Honor an explicit platform choice even when site customization
# pre-imported jax with another backend registered.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import argparse
import itertools

import numpy as np

from defer_tpu.api import run_local_inference
from defer_tpu.models import get_model


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument(
        "--images",
        default=os.path.join(os.path.dirname(__file__), "images"),
        help="directory of real images for the looped batch; "
        "--synthetic feeds ones instead",
    )
    ap.add_argument("--synthetic", action="store_true")
    args = ap.parse_args()

    model = get_model(args.model)
    example = None
    is_image_model = (
        len(model.input_shape) == 3 and model.input_shape[-1] == 3
    )
    if not args.synthetic and is_image_model:
        # The reference preprocesses one real image and loops on it
        # (reference src/local_infer.py:10-14); same here, batched,
        # with the preprocessing the model's weights expect.
        from defer_tpu.runtime.data import (
            imagenet_preprocess,
            load_image_dir,
            preprocess_mode,
        )

        imgs = itertools.cycle(load_image_dir(args.images))
        example = np.concatenate(
            [
                imagenet_preprocess(
                    next(imgs),
                    size=model.input_shape[0],
                    mode=preprocess_mode(model.name),
                )
                for _ in range(args.batch)
            ]
        )

    stats = run_local_inference(
        model,
        batch_size=args.batch,
        duration_s=args.minutes * 60,
        example=example,
    )
    print(f"{stats['count']:.0f} results in {args.minutes} min")
    print(f"Throughput: {stats['items_per_sec']:.2f} images/sec")


if __name__ == "__main__":
    main()
