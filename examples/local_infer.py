#!/usr/bin/env python
"""Single-device baseline — the reference's `local_infer.py`, TPU-native
(reference src/local_infer.py:16-23: loop model.predict, count results).

This defines the denominator of every pipeline speedup claim.

    python examples/local_infer.py --model resnet50 --minutes 1
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import jax

# Honor an explicit platform choice even when site customization
# pre-imported jax with another backend registered.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import argparse

from defer_tpu.api import run_local_inference
from defer_tpu.models import get_model


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--batch", type=int, default=1)
    args = ap.parse_args()

    stats = run_local_inference(
        get_model(args.model),
        batch_size=args.batch,
        duration_s=args.minutes * 60,
    )
    print(f"{stats['count']:.0f} results in {args.minutes} min")
    print(f"Throughput: {stats['items_per_sec']:.2f} images/sec")


if __name__ == "__main__":
    main()
