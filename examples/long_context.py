#!/usr/bin/env python
"""Long-context attention demo: ring vs Ulysses sequence parallelism.

The reference has no notion of a sequence axis at all (SURVEY.md §5);
this driver shows the framework's long-context path: a sequence far
too big for one device's O(S^2) score matrix, sharded over a `seq`
mesh axis, attended with ring attention (K/V blocks rotating on ICI
with a streaming-softmax accumulator) or Ulysses (all_to_all to
head-sharding and back), and checked against the unsharded reference
when it fits.

    # 8-way CPU emulation (no hardware needed):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context.py --seq 8192 --strategy ring
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from defer_tpu.utils.platform import honor_env_platform

honor_env_platform()

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.sequence import make_sharded_attention


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--strategy", choices=["ring", "ulysses"], default="ring")
    ap.add_argument("--causal", action="store_true")
    ap.add_argument(
        "--check",
        action="store_true",
        help="compare against unsharded attention (needs the full S^2 "
        "score matrix on one device — only for small --seq)",
    )
    args = ap.parse_args()

    devs = jax.devices()
    n = len(devs)
    if args.seq % n:
        raise SystemExit(f"--seq {args.seq} must divide by {n} devices")
    mesh = make_mesh({"seq": n}, devs)
    print(
        f"{args.strategy} attention over {n} devices "
        f"({devs[0].device_kind}); S={args.seq} "
        f"(S_local={args.seq // n}), H={args.heads}, Dh={args.head_dim}"
    )

    shape = (args.batch, args.heads, args.seq, args.head_dim)
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)

    attn = make_sharded_attention(
        mesh, strategy=args.strategy, causal=args.causal
    )
    out = attn(q, k, v)
    out.block_until_ready()  # compile
    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        out = attn(q, k, v)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    toks = args.batch * args.seq
    print(
        f"{dt * 1e3:.1f} ms/step, {toks / dt:,.0f} tokens/sec; "
        f"score matrix never materialized "
        f"({args.seq}^2 x {args.heads} heads would be "
        f"{args.seq**2 * args.heads * 4 / 1e9:.1f} GB in fp32)"
    )

    if args.check:
        from defer_tpu.ops.attention import attention_reference

        want = attention_reference(
            q.astype(jnp.float32),
            k.astype(jnp.float32),
            v.astype(jnp.float32),
            causal=args.causal,
        )
        err = float(
            jnp.max(jnp.abs(out.astype(jnp.float32) - want))
        )
        print(f"max abs err vs unsharded reference: {err:.4f}")
        assert err < 0.05, "sequence-parallel attention diverged"
        print("matches unsharded reference")


if __name__ == "__main__":
    main()
