#!/usr/bin/env python
"""Continuous-batching decode serving demo.

A stream of generation requests with mixed prompt lengths and step
counts is served through a fixed set of batch slots: requests admit
into free slots mid-flight (bucketed prefill + K/V lane insertion)
and every decode tick advances ALL active requests through one weight
read (runtime/decode_server.py). Compare against the per-request
baseline the reference's serving model implies (one stream at a time,
reference src/test.py:30-41).

    python examples/serve_decode.py --family llama --requests 16 \\
        --slots 4
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import time


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--family", choices=("gpt", "llama"), default="llama")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--ffn", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--prefix", type=int, default=0,
                    help="shared system-prompt length: its K/V rows "
                    "are prefilled once and reused by every admission")
    ap.add_argument("--adapters", type=int, default=0,
                    help="multi-LoRA: attach this many random adapter "
                    "banks and round-robin requests across them "
                    "(id 0 = base model)")
    ap.add_argument("--stop-demo", action="store_true",
                    help="multi-token stop sequences: learn a 2-token "
                    "stop from request 0's greedy stream, re-serve it "
                    "with that stop and show it terminates mid-budget "
                    "with its output ending in the stop sequence")
    ap.add_argument("--check", action="store_true",
                    help="verify the echoed prompt comes back verbatim "
                    "and every generated token is a valid greedy choice "
                    "under a tie tolerance (see the comment at the "
                    "check site for why exact solo-decode equality is "
                    "ill-conditioned at this scale)")
    args = ap.parse_args()
    if args.prefix and args.adapters:
        ap.error(
            "--prefix and --adapters are mutually exclusive (the "
            "shared prefix K/V would be adapter-dependent)"
        )

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.parallel.transformer_stack import TransformerConfig
    from defer_tpu.runtime.decode_server import DecodeServer

    if args.family == "llama":
        from defer_tpu.models.llama import llama_config

        cfg = llama_config(
            num_layers=args.layers, dim=args.dim, num_heads=args.heads,
            num_kv_heads=max(1, args.heads // 4), ffn_dim=args.ffn,
            vocab_size=args.vocab, max_len=args.max_len,
        )
    else:
        cfg = TransformerConfig(
            num_layers=args.layers, dim=args.dim, num_heads=args.heads,
            ffn_dim=args.ffn, vocab_size=args.vocab,
            max_len=args.max_len, norm_style="pre",
        )
    dec = GptDecoder(cfg)
    params = dec.cast_params(dec.init(jax.random.key(0)))

    adapter_of = lambda i: 0  # noqa: E731 — overridden below
    if args.adapters:
        import dataclasses as _dc

        from defer_tpu.parallel.lora import stack_adapters
        from defer_tpu.parallel.transformer_stack import init_stack

        lora_cfg = _dc.replace(
            cfg, lora_rank=8, lora_alpha=16.0,
            lora_targets=("wq", "wv"),
        )
        trees = []
        for a in range(args.adapters):
            full = init_stack(jax.random.key(100 + a), lora_cfg)
            lkeys = sorted(k for k in full if ":" in k)
            trees.append({
                "stack": {
                    k: (full[k] if k.endswith(":a")
                        else jax.random.normal(
                            # Stable per-tensor fold so same-shape b
                            # banks are independent AND reproducible.
                            jax.random.fold_in(
                                jax.random.key(100 + a), 1 + lkeys.index(k)
                            ),
                            full[k].shape,
                        ) * 0.02)
                    for k in lkeys
                }
            })
        params = stack_adapters(params, trees, lora_cfg)
        adapter_of = lambda i: i % (args.adapters + 1)  # noqa: E731

    # Mixed workload: prompt lengths 4..67, steps 8..39.
    reqs = []
    for i in range(args.requests):
        t0 = 4 + (i * 9) % 64
        steps = 8 + (i * 13) % 32
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i), (1, t0), 0, args.vocab
        )
        reqs.append((prompt, steps))

    prefix = None
    if args.prefix:
        prefix = jax.random.randint(
            jax.random.key(2), (1, args.prefix), 0, args.vocab
        )
    srv = DecodeServer(
        dec, params, max_batch=args.slots, prefix_ids=prefix
    )
    rids = [
        srv.submit(p, s, adapter_id=adapter_of(i))
        for i, (p, s) in enumerate(reqs)
    ]
    t0 = time.perf_counter()
    done = srv.run()
    jax.block_until_ready(done[rids[-1]])
    dt = time.perf_counter() - t0
    total_tokens = sum(s for _, s in reqs)
    print(
        f"{args.requests} requests / {args.slots} slots: "
        f"{total_tokens} tokens in {dt:.2f}s "
        f"({total_tokens / dt:,.1f} tok/s), {srv.ticks} batched ticks "
        f"vs {srv.solo_steps} solo steps "
        f"({srv.solo_steps / max(1, srv.ticks):.1f}x tick sharing)"
        + (
            f", {srv.prefix_len * len(reqs)} prefill tokens reused"
            if args.prefix
            else ""
        )
    )

    if args.stop_demo:
        import numpy as np

        p0, s0 = reqs[0]
        base = np.asarray(done[rids[0]])[0]
        gen0 = base[p0.shape[1]:]
        if len(gen0) < 4:
            print("stop-demo: request 0 too short to demo, skipping")
        else:
            # The pair at generated positions 1-2 is (one of) the
            # earliest 2-token windows, so serving with it as a stop
            # sequence must terminate at or before position 2.
            stop = [int(gen0[1]), int(gen0[2])]
            srv2 = DecodeServer(
                dec, params, max_batch=args.slots, prefix_ids=prefix
            )
            rid = srv2.submit(
                p0, s0, adapter_id=adapter_of(0), stop=[stop]
            )
            out = np.asarray(srv2.run()[rid])[0]
            emitted = len(out) - p0.shape[1]
            assert emitted < s0, (emitted, s0)
            assert list(out[-2:]) == stop, (out[-2:], stop)
            print(
                f"stop-demo: stop={stop} terminated request 0 after "
                f"{emitted} of {s0} budgeted tokens, output ends "
                "with the stop sequence"
            )

    if args.check:
        # Token-level equality with a solo decode is ill-conditioned at
        # this scale: random weights leave near-ties everywhere in a
        # 32k-vocab softmax, and the bucketed/offset prefill computes
        # the same math in different shapes, so low-order float bits
        # legitimately flip argmax at a tie (the unit tests pin exact
        # equality at tiny scale, where it is stable). The meaningful
        # any-scale contract: every emitted token must be a valid
        # greedy choice — its teacher-forced reference logit within a
        # tie tolerance of the max.
        import numpy as np

        tol = 0.08  # generous for bf16 compute
        checked = 0
        for i, ((p, s), rid) in enumerate(zip(reqs, rids)):
            if adapter_of(i) != 0:
                # reference_logits carries no adapter id, so greedy
                # validity can only be checked for base-model requests
                # (the unit tests pin tenant exactness at small scale).
                continue
            out = done[rid]  # [1, t0 + s] (suffix + generation)
            # The echoed prompt must come back verbatim — greedy
            # validity below only covers the generated tail.
            np.testing.assert_array_equal(
                np.asarray(out[:, : p.shape[1]]), np.asarray(p)
            )
            full = (
                jnp.concatenate([prefix, out], axis=1)
                if prefix is not None
                else out
            )
            logits = dec.reference_logits(params, full[:, :-1])
            t_gen0 = full.shape[1] - s  # first generated position
            for j in range(s):
                pos = t_gen0 - 1 + j
                row = np.asarray(logits[0, pos], np.float32)
                tok = int(full[0, t_gen0 + j])
                gap = float(row.max() - row[tok])
                assert gap <= tol, (
                    f"request {rid}: token {j} (id {tok}) is {gap:.3f} "
                    "below the greedy max — not a valid greedy choice"
                )
                checked += 1
        print(
            f"all {checked} generated tokens are valid greedy choices "
            f"(tie tolerance {tol})"
        )


if __name__ == "__main__":
    main()
