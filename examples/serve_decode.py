#!/usr/bin/env python
"""Continuous-batching decode serving demo.

A stream of generation requests with mixed prompt lengths and step
counts is served through a fixed set of batch slots: requests admit
into free slots mid-flight (bucketed prefill + K/V lane insertion)
and every decode tick advances ALL active requests through one weight
read (runtime/decode_server.py). Compare against the per-request
baseline the reference's serving model implies (one stream at a time,
reference src/test.py:30-41).

    python examples/serve_decode.py --family llama --requests 16 \\
        --slots 4
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import time


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--family", choices=("gpt", "llama"), default="llama")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--ffn", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--check", action="store_true",
                    help="verify every output against a solo decode")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.parallel.transformer_stack import TransformerConfig
    from defer_tpu.runtime.decode_server import DecodeServer

    if args.family == "llama":
        from defer_tpu.models.llama import llama_config

        cfg = llama_config(
            num_layers=args.layers, dim=args.dim, num_heads=args.heads,
            num_kv_heads=max(1, args.heads // 4), ffn_dim=args.ffn,
            vocab_size=args.vocab, max_len=args.max_len,
        )
    else:
        cfg = TransformerConfig(
            num_layers=args.layers, dim=args.dim, num_heads=args.heads,
            ffn_dim=args.ffn, vocab_size=args.vocab,
            max_len=args.max_len, norm_style="pre",
        )
    dec = GptDecoder(cfg)
    params = dec.cast_params(dec.init(jax.random.key(0)))

    # Mixed workload: prompt lengths 4..67, steps 8..39.
    reqs = []
    for i in range(args.requests):
        t0 = 4 + (i * 9) % 64
        steps = 8 + (i * 13) % 32
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i), (1, t0), 0, args.vocab
        )
        reqs.append((prompt, steps))

    srv = DecodeServer(dec, params, max_batch=args.slots)
    rids = [srv.submit(p, s) for p, s in reqs]
    t0 = time.perf_counter()
    done = srv.run()
    jax.block_until_ready(done[rids[-1]])
    dt = time.perf_counter() - t0
    total_tokens = sum(s for _, s in reqs)
    print(
        f"{args.requests} requests / {args.slots} slots: "
        f"{total_tokens} tokens in {dt:.2f}s "
        f"({total_tokens / dt:,.1f} tok/s), {srv.ticks} batched ticks "
        f"vs {srv.solo_steps} solo steps "
        f"({srv.solo_steps / max(1, srv.ticks):.1f}x tick sharing)"
    )

    if args.check:
        import numpy as np

        for (p, s), rid in zip(reqs, rids):
            want = dec.generate(params, p, s)
            np.testing.assert_array_equal(
                np.asarray(done[rid]), np.asarray(want)
            )
        print(f"all {args.requests} outputs equal solo decodes")


if __name__ == "__main__":
    main()
