#!/usr/bin/env python
"""Serving-loop demo: batch-1 clients, dynamically batched device work.

The reference's driver streams one frame per queue item (reference
src/test.py:52-54) — the natural serving shape, but worth ~2% of a TPU
chip (bench sweep: ~255 img/s at batch 1 vs ~13,000 at batch 256 on
v5e). This driver keeps the exact same client contract (put one item,
get one result, in order) and lets the runtime coalesce items into
device batches under a latency SLO:

    python examples/serving_batched.py --model resnet50 \
        --batch-size 32 --wait-ms 5 --seconds 20

Prints per-item latency percentiles and throughput with batching on
vs off, so the SLO/throughput trade is visible.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import argparse
import queue
import threading
import time

import jax.numpy as jnp

from defer_tpu.api import DEFER
from defer_tpu.config import DeferConfig
from defer_tpu.models import get_model


def run(model, params, cuts, cfg, seconds: float) -> dict:
    inq: "queue.Queue" = queue.Queue(maxsize=256)
    outq: "queue.Queue" = queue.Queue()
    defer = DEFER(config=cfg)
    worker = threading.Thread(
        target=defer.run_defer,
        args=(model, cuts, inq, outq),
        kwargs={"params": params},
        daemon=True,
    )
    worker.start()

    # Respect the model's declared input dtype/shape (token-id models
    # take integers — example_input handles that).
    x = model.example_input(1)
    latencies: list[float] = []
    done = threading.Event()
    sent = 0

    def drain() -> None:
        while not done.is_set() or not outq.empty():
            try:
                outq.get(timeout=0.1)
            except queue.Empty:
                continue
            if t_sent:
                latencies.append(time.perf_counter() - t_sent.popleft())

    import collections

    t_sent: "collections.deque[float]" = collections.deque()
    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()

    def guarded_put(item) -> None:
        # Bounded put + liveness check: if the worker died (bad cuts,
        # device failure past the retry budget) the feed must error
        # out, not deadlock on a full queue forever.
        while True:
            try:
                inq.put(item, timeout=1.0)
                return
            except queue.Full:
                if not worker.is_alive():
                    raise RuntimeError(
                        "pipeline worker died; see its traceback above"
                    ) from None

    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        t_sent.append(time.perf_counter())
        guarded_put(x)
        sent += 1
    guarded_put(None)
    worker.join(timeout=600)
    clean = not worker.is_alive()
    done.set()
    drainer.join(timeout=60)
    dt = time.perf_counter() - t0
    latencies.sort()
    n = len(latencies)
    stats = {
        "items_per_sec": n / dt,
        "p50_ms": latencies[n // 2] * 1e3 if n else None,
        "p99_ms": latencies[min(n - 1, int(n * 0.99))] * 1e3 if n else None,
        "completed": n,
        "sent": sent,
    }
    if not clean:
        stats["warning"] = "worker did not exit within 600s; stats truncated"
    elif n != sent:
        # Elastic re-dispatch may drop in-flight items; their stale
        # send-times then skew every later latency pairing.
        stats["warning"] = (
            f"{sent - n} item(s) dropped (pipeline recovery?); latency "
            "percentiles may be skewed"
        )
    return stats


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--cuts", default=None, help="comma-separated, or 'auto'")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--wait-ms", type=float, default=5.0)
    ap.add_argument("--seconds", type=float, default=10.0)
    args = ap.parse_args()

    model = get_model(args.model)
    params = model.init(jax.random.key(0))
    cuts = (
        args.cuts
        if args.cuts in (None, "auto")
        else [c.strip() for c in args.cuts.split(",") if c.strip()]
    )

    base = DeferConfig(compute_dtype=jnp.bfloat16)
    batched = base.replace(
        dynamic_batch_size=args.batch_size,
        batch_wait_s=args.wait_ms / 1e3,
    )
    print(f"batching OFF ({args.seconds:.0f}s)...")
    off = run(model, params, cuts, base, args.seconds)
    print(f"  {off}")
    print(
        f"batching ON (<= {args.batch_size}/dispatch, "
        f"{args.wait_ms:.1f} ms SLO, {args.seconds:.0f}s)..."
    )
    on = run(model, params, cuts, batched, args.seconds)
    print(f"  {on}")
    if off["items_per_sec"]:
        print(
            f"throughput: {on['items_per_sec'] / off['items_per_sec']:.1f}x "
            "with batching"
        )


if __name__ == "__main__":
    main()
