#!/usr/bin/env python
"""Distributed training example: one jitted step over a dp x pp x tp
mesh (beyond the reference's inference-only scope, SURVEY.md §5).

Run on hardware, or emulate a slice on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/spmd_train.py --steps 5
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import jax

# Honor an explicit platform choice even when site customization
# pre-imported jax with another backend registered.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import argparse
import time

import jax.numpy as jnp
import optax

from defer_tpu.models.bert import SpmdBert
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.train import make_train_step
from defer_tpu.parallel.transformer_stack import TransformerConfig


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument(
        "--ckpt-dir",
        default=None,
        help="save a sharded distributed checkpoint here every "
        "--ckpt-every steps and resume from it if present",
    )
    ap.add_argument("--ckpt-every", type=int, default=10)
    ap.add_argument(
        "--remat",
        action="store_true",
        help="rematerialize blocks on backward (jax.checkpoint): "
        "O(1)-block activation memory per stage for one extra forward",
    )
    ap.add_argument(
        "--lm",
        action="store_true",
        help="next-token language-model objective (causal stack, "
        "weight-tied head) instead of CLS classification; the trained "
        "tree serves directly on the KV-cache decoder",
    )
    ap.add_argument(
        "--zero1",
        action="store_true",
        help="shard optimizer moments over the data axis (ZeRO-1)",
    )
    ap.add_argument(
        "--fsdp",
        action="store_true",
        help="shard stack weights over the data axis, all-gathered "
        "just in time per block (FSDP)",
    )
    args = ap.parse_args()

    n_dev = len(jax.devices())
    dp = max(1, n_dev // (args.stages * args.tp))
    mesh = make_mesh(
        {"data": dp, "stage": args.stages, "model": args.tp},
        jax.devices()[: dp * args.stages * args.tp],
    )
    cfg = TransformerConfig(
        num_layers=args.layers,
        dim=args.dim,
        num_heads=4,
        ffn_dim=4 * args.dim,
        vocab_size=1024,
        max_len=args.seq,
        remat=args.remat,
        norm_style="pre" if args.lm else "post",
        causal=args.lm,
    )
    sb = SpmdBert(mesh, cfg, fsdp=args.fsdp)
    if args.lm:
        from defer_tpu.parallel.train import make_lm_train_step

        init_state, train_step = make_lm_train_step(
            sb, optax.adamw(1e-3), zero1=args.zero1
        )
    else:
        init_state, train_step = make_train_step(
            sb, optax.adamw(1e-3), num_classes=8, zero1=args.zero1
        )
    state = init_state(jax.random.key(0))

    import glob

    if args.ckpt_dir and glob.glob(
        os.path.join(args.ckpt_dir, "shards-*.defer")
    ):
        from defer_tpu.runtime.checkpoint import restore_sharded

        state = restore_sharded(args.ckpt_dir, state)
        print(f"resumed sharded state from {args.ckpt_dir}")

    num_mb = args.stages + 2
    batch = 4 * dp
    key = jax.random.key(1)
    print(f"mesh dp={dp} pp={args.stages} tp={args.tp}; "
          f"{num_mb} microbatches of {batch}x{args.seq}")

    t0 = time.perf_counter()
    for step in range(args.steps):
        key, k1, k2 = jax.random.split(key, 3)
        ids = jax.random.randint(k1, (num_mb, batch, args.seq), 0, cfg.vocab_size)
        if args.lm:
            state, loss = train_step(state, ids)
        else:
            labels = jax.random.randint(k2, (num_mb, batch), 0, 8)
            state, loss = train_step(state, ids, labels)
        if step in (0, args.steps - 1) or step % 10 == 0:
            print(f"step {step}: loss {float(loss):.4f}")
        if args.ckpt_dir and (step + 1) % args.ckpt_every == 0:
            from defer_tpu.runtime.checkpoint import save_sharded

            # The step is the cross-process save id: restore rejects a
            # directory where only some processes finished a save.
            save_sharded(args.ckpt_dir, state, save_id=step)
            print(f"saved sharded checkpoint at step {step}")
    dt = time.perf_counter() - t0
    tokens = args.steps * num_mb * batch * args.seq
    print(f"{tokens / dt:.0f} tokens/sec over {args.steps} steps")


if __name__ == "__main__":
    main()
